"""Disaggregation rung: phase-split fleet vs unified under prefill bursts.

PR 18's serving claim — splitting the fleet into prefill and decode
pools isolates decode tail latency from prefill storms, at matched
replica count, with the output streams bitwise unchanged — is MEASURED
here on the prefill-heavy MMPP mix
(:func:`torchgpipe_tpu.fleet.trace.prefill_heavy_config`: a
short-prompt decode-dominated base load punctuated by bursts of LONG
prompts with small budgets).  Two rungs serve the SAME trace at the
same replica count:

* ``unified`` — 2 unified replicas: every replica interleaves burst
  prefill chunks with its live decode rounds, so each storm steals
  decode iterations from in-flight streams;
* ``disagg``  — 1 prefill + 1 decode replica: storms land in the
  prefill pool, finished prompts migrate (KV rows through the
  fixed-shape ``migrate_ingest`` program), and the decode replica runs
  NOTHING but decode rounds.

Measurement contract:

* **Exactness is the hard gate** — both rungs must emit BITWISE
  identical per-request token streams, and the disagg rung must
  actually migrate (``fleet_migrations`` > 0); any divergence exits
  non-zero, no numbers published.
* **Tail latency is measured on a per-replica STEP clock** — each
  engine's :class:`~torchgpipe_tpu.serving.metrics.ServingMetrics`
  reads a virtual clock that advances 1.0 per productive step of ITS
  OWN engine, so TPOT is "engine steps per emitted token": exactly 1.0
  when a replica runs only decode rounds, ~2.0 when prefill work
  interleaves.  Deterministic — a property of trace + routing, not of
  host speed (wall seconds are published unguarded alongside).
* **The headline gate is the isolation claim** — the disagg rung's
  decode TPOT p95 must stay at the 1 step/token floor under the burst,
  while the unified rung's must measurably degrade (>= 1.1x the
  disagg figure); a trace too calm to show the effect fails rather
  than publishing a vacuous win.
* **The timed region is compile-free** — a full warm pass precedes it
  and every program's trace count must be unchanged afterwards.
* **Honesty counters ride along** — the generator's
  ``skipped_too_long`` must be 0 (every generated request fits
  ``max_len``) and the trace must contain actual burst arrivals.

Usage::

    env JAX_PLATFORMS=cpu python -m benchmarks.disagg_trace
    env JAX_PLATFORMS=cpu python bench.py --disagg    # one JSON line
"""

from __future__ import annotations

import argparse
import json
import os
import time
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

from torchgpipe_tpu import fleet
from torchgpipe_tpu.layers import sequential_init
from torchgpipe_tpu.models.transformer import TransformerConfig, llama
from torchgpipe_tpu.obs import MetricsRegistry
from torchgpipe_tpu.serving import Engine, ServingMetrics

VOCAB = 64
MAX_LEN = 48


class _StepClock:
    """A per-replica virtual clock: t advances 1.0 per productive step
    of the engine it is attached to."""

    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def _make_trace(args: argparse.Namespace) -> Tuple[
    List[fleet.TraceRequest], fleet.TraceStats
]:
    stats = fleet.TraceStats()
    cfg = fleet.prefill_heavy_config(
        args.requests, seed=args.seed, max_len=MAX_LEN, vocab=VOCAB,
    )
    return list(fleet.synthetic_trace(cfg, stats)), stats


def _run_fleet(cfg: TransformerConfig, flat: Any,
               reqs: List[fleet.TraceRequest], *,
               roles: Dict[str, str], slots: int,
               seed: int) -> Dict[str, Any]:
    """One rung: build the fleet, warm it with a full untimed pass
    (every program — including ``migrate_ingest`` — compiles outside
    the timed region), swap in fresh step-clock metrics, replay."""
    reg = MetricsRegistry()
    warm_metrics = ServingMetrics()
    engines = {
        name: Engine(cfg, flat, num_slots=slots, max_len=MAX_LEN,
                     prefill_chunk=8, role=role, metrics=warm_metrics,
                     registry=reg.labeled(replica=name))
        for name, role in roles.items()
    }
    router = fleet.Router(engines, registry=reg, seed=seed)
    for i, req in enumerate(reqs):
        router.submit(req.prompt, req.max_new_tokens,
                      rid=f"warm-{i}", session=req.session)
        router.step()
    while router.run() != "idle":
        pass

    # Per-replica step clocks + fresh metrics: the timed region's TPOT
    # is engine-steps-per-token, deterministic across hosts.
    clocks: Dict[str, _StepClock] = {}
    for name, rep in router.replicas.items():
        clock = clocks[name] = _StepClock()
        rep.engine.metrics = ServingMetrics(clock=clock)

        def stepper(orig=rep.engine.step, c=clock):
            ran = orig()
            if ran:
                c.t += 1.0
            return ran

        rep.engine.step = stepper
    warm_migrations = int(reg.counter("fleet_migrations").value())
    warm_traces = {
        name: dict(rep.engine.trace_counts)
        for name, rep in router.replicas.items()
    }

    rids: List[str] = []
    t0 = time.perf_counter()
    for i, req in enumerate(reqs):
        rids.append(router.submit(req.prompt, req.max_new_tokens,
                                  rid=f"q{i}", session=req.session))
        router.step()
    while router.run() != "idle":
        pass
    dt = time.perf_counter() - t0

    for name, rep in router.replicas.items():
        if dict(rep.engine.trace_counts) != warm_traces[name]:
            raise SystemExit(
                f"COMPILE-FREE FAIL: replica {name} traced a program "
                f"inside the timed region: {dict(rep.engine.trace_counts)}"
                f" vs warm {warm_traces[name]}"
            )

    outs = [router.result(r).tolist() for r in rids]
    # TPOT samples in step units, pooled across replicas: a request's
    # decode gap lives on the replica that finished its stream.
    tpots = [
        r.tpot
        for rep in router.replicas.values()
        for r in rep.engine.metrics.requests.values()
        if r.status == "finished" and r.tpot is not None
    ]
    if not tpots:
        raise SystemExit("no request produced a TPOT sample")
    toks = sum(len(o) for o in outs)
    return {
        "outs": outs,
        "seconds": dt,
        "tokens": toks,
        "tokens_per_sec": toks / dt,
        "tpot_steps_p50": float(np.percentile(tpots, 50)),
        "tpot_steps_p95": float(np.percentile(tpots, 95)),
        "tpot_samples": len(tpots),
        "migrations": int(reg.counter("fleet_migrations").value())
        - warm_migrations,
        "steps": {n: c.t for n, c in clocks.items()},
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=40)
    ap.add_argument("--seed", type=int, default=3)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--margin", type=float, default=1.1,
                    help="unified decode TPOT p95 must exceed the "
                    "disagg figure by this factor — the 'unified "
                    "measurably degrades' half of the claim")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line (bench.py --disagg)")
    args = ap.parse_args()

    cfg = TransformerConfig(
        vocab=VOCAB, dim=96, n_layers=4, n_heads=4, n_kv_heads=2
    )
    flat, _, _ = sequential_init(
        llama(cfg), jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((2, 8), jnp.int32),
    )
    reqs, stats = _make_trace(args)
    if stats.skipped_too_long:
        raise SystemExit(
            f"trace generator skipped {stats.skipped_too_long} "
            f"requests — the preset must fit max_len={MAX_LEN}"
        )
    if not stats.burst_arrivals:
        raise SystemExit(
            "trace contains no burst arrivals — the prefill-storm "
            "claim would be vacuous; pick another seed"
        )

    unified = _run_fleet(
        cfg, flat, reqs, slots=args.slots, seed=args.seed,
        roles={"u0": "unified", "u1": "unified"},
    )
    disagg = _run_fleet(
        cfg, flat, reqs, slots=args.slots, seed=args.seed,
        roles={"p0": "prefill", "d0": "decode"},
    )

    # HARD GATE 1: bitwise equality — the phase split changes nothing
    # in any output stream.
    if disagg["outs"] != unified["outs"]:
        bad = next(
            i for i, (a, b) in enumerate(zip(disagg["outs"],
                                             unified["outs"]))
            if a != b
        )
        raise SystemExit(
            f"EXACTNESS FAIL: disagg rung diverged from unified at "
            f"request {bad}: {disagg['outs'][bad]} vs "
            f"{unified['outs'][bad]}"
        )

    # HARD GATE 2: the split actually migrated every stream.
    if disagg["migrations"] < len(reqs):
        raise SystemExit(
            f"disagg rung migrated {disagg['migrations']} of "
            f"{len(reqs)} requests — the handoff path was not on"
        )

    # HARD GATE 3 (headline): the decode pool holds the 1 step/token
    # floor under the prefill burst; unified measurably degrades.
    if disagg["tpot_steps_p95"] > 1.0 + 1e-9:
        raise SystemExit(
            f"ISOLATION FAIL: disagg decode TPOT p95 "
            f"{disagg['tpot_steps_p95']:.3f} steps/token — the decode "
            "pool lost iterations to prefill work"
        )
    if unified["tpot_steps_p95"] < args.margin * disagg["tpot_steps_p95"]:
        raise SystemExit(
            f"unified rung did not measurably degrade "
            f"(p95 {unified['tpot_steps_p95']:.3f} vs disagg "
            f"{disagg['tpot_steps_p95']:.3f} x margin {args.margin}) — "
            "the trace shows no prefill pressure; pick another seed"
        )

    out = {
        "bench": "disagg-trace",
        "platform": jax.devices()[0].platform,
        "requests": args.requests,
        "seed": args.seed,
        "slots_per_replica": args.slots,
        "replicas": 2,
        "trace": {
            "generated": stats.generated,
            "skipped_too_long": stats.skipped_too_long,
            "burst_arrivals": stats.burst_arrivals,
            "burst_prompt_tokens": stats.burst_prompt_tokens,
            "total_prompt_tokens": stats.total_prompt_tokens,
        },
        "unified": _pub(unified),
        "disagg": {**_pub(disagg), "migrations": disagg["migrations"]},
        "isolation": {
            "unified_tpot_steps_p95": round(
                unified["tpot_steps_p95"], 3
            ),
            "disagg_tpot_steps_p95": round(
                disagg["tpot_steps_p95"], 3
            ),
            "margin": args.margin,
            "held": True,
        },
        "exactness_gated": True,
        "validated": True,
    }
    if args.json:
        print(json.dumps(out), flush=True)
        return
    print(
        f"disagg-trace: {stats.generated} requests "
        f"({stats.burst_arrivals} burst arrivals, "
        f"{stats.burst_prompt_tokens} burst prompt tokens) at 2 "
        f"replicas x {args.slots} slots\n"
        f"  unified  tpot {unified['tpot_steps_p50']:.3f}/"
        f"{unified['tpot_steps_p95']:.3f} steps p50/p95  "
        f"{unified['tokens_per_sec']:8.1f} tok/s wall\n"
        f"  disagg   tpot {disagg['tpot_steps_p50']:.3f}/"
        f"{disagg['tpot_steps_p95']:.3f} steps p50/p95  "
        f"{disagg['tokens_per_sec']:8.1f} tok/s wall  "
        f"({disagg['migrations']} handoffs)\n"
        f"  decode tail isolated: disagg holds the 1 step/token floor "
        f"under the burst, unified degrades "
        f"{unified['tpot_steps_p95'] / disagg['tpot_steps_p95']:.2f}x; "
        f"outputs bitwise-identical across the split",
        flush=True,
    )


def _pub(r: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "tokens_per_sec": round(r["tokens_per_sec"], 1),
        "seconds": round(r["seconds"], 4),
        "tokens": r["tokens"],
        "tpot_steps_p50": round(r["tpot_steps_p50"], 3),
        "tpot_steps_p95": round(r["tpot_steps_p95"], 3),
        "tpot_samples": r["tpot_samples"],
        "steps": r["steps"],
    }


if __name__ == "__main__":
    main()
