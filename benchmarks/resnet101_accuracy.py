"""ResNet-101 accuracy benchmark: pipeline-transparent training.

Reference: benchmarks/resnet101-accuracy/main.py:22-125 — 90-epoch ImageNet
training comparing naive / data-parallel / GPipe at batch 256/1K/4K with
gradual-warmup LR scaling, existing to *prove transparency* (the pipeline
trains to the same accuracy as the plain model; docs/benchmarks.rst:13-19).

This driver trains on an image-folder dataset when given (``--data-dir``
with numpy ``train_x.npy``/``train_y.npy``) and otherwise on a synthetic
deterministic dataset — the transparency claim is checked the same way:
run with ``--experiment naive`` and ``--experiment pipeline-4`` and compare
curves.
"""

from __future__ import annotations

import os
import time

import click
import jax
import jax.numpy as jnp
import numpy as np

from benchmarks.common import build_gpipe, hr_time, softmax_xent
from torchgpipe_tpu.models import resnet101

EXPERIMENTS = {
    "naive-256": (1, 256, 1),
    # BN-noise CONTROL arm: un-pipelined but micro-batched like
    # pipeline-256 (chunks=8), so BatchNorm normalizes the same
    # micro-batches.  pipeline-256 must match THIS arm tightly — the
    # "pipeline converges slower because of micro-batch BN statistics"
    # explanation measured as an equivalence rather than narrated
    # (round-3 addition; the naive-vs-pipeline gap is then attributable
    # to BN alone).
    "naive-mbn-256": (1, 256, 8),
    "pipeline-256": (4, 256, 8),
    "pipeline-1k": (8, 1024, 32),
    "pipeline-4k": (8, 4096, 128),
}


def _dataset(data_dir, n, image, classes, seed=0):
    if data_dir == "sklearn-digits":
        # REAL offline data (the only real image dataset shipped in this
        # container): scikit-learn's handwritten digits — 1797 8x8
        # grayscale images, 10 classes.  Upsampled (nearest) to ``image``
        # and replicated to 3 channels so the same ResNet stem applies;
        # standardized per-dataset.  The eval-mode accuracy story needs
        # real generalizable structure, which per-class-template noise
        # only approximates (round-3 verdict weak #3).
        from sklearn.datasets import load_digits

        d = load_digits()
        reps = max(1, image // 8)
        x = np.kron(
            d.images.astype(np.float32), np.ones((1, reps, reps), np.float32)
        )[:, :image, :image]
        x = (x - x.mean()) / (x.std() + 1e-8)
        x = np.repeat(x[..., None], 3, axis=-1)
        y = d.target.astype(np.int32)
        rs = np.random.RandomState(seed)
        order = rs.permutation(len(y))[:n]
        return jnp.asarray(x[order]), jnp.asarray(y[order])
    if data_dir:
        x = np.load(os.path.join(data_dir, "train_x.npy"))
        y = np.load(os.path.join(data_dir, "train_y.npy"))
        return jnp.asarray(x), jnp.asarray(y)
    # Class-SEPARABLE synthetic data (per-class template + noise), not pure
    # noise: eval-mode accuracy then reflects real learning instead of
    # per-image memorization that BN running statistics cannot reproduce —
    # pure-noise data left eval top-1 pinned at the 1/classes floor even at
    # train loss 0.19 (round-2 weakness; the transparency comparison needs
    # accuracies OFF the floor to be informative).
    rs = np.random.RandomState(seed)
    templates = rs.randn(classes, image, image, 3).astype(np.float32)
    y = rs.randint(0, classes, n).astype(np.int32)
    x = templates[y] + 0.7 * rs.randn(n, image, image, 3).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


def _loss_with_logits(out, tgt):
    """Loss with the training forward's logits on the aux channel, so
    train-mode accuracy costs no extra forward pass.  Module-level (not a
    per-step closure): the engine's jit cache keys on the loss_fn object,
    and a fresh closure each step would force a re-trace every step."""
    return softmax_xent(out, tgt), out


@click.command()
@click.argument("experiment", type=click.Choice(sorted(EXPERIMENTS)))
@click.option("--epochs", default=3)
@click.option("--data-dir", default=None, type=str)
@click.option("--image", default=64, help="image size (synthetic data)")
@click.option("--dataset-size", default=512)
@click.option("--classes", default=100)
@click.option("--lr", default=0.1)
@click.option("--warmup-epochs", default=1, help="gradual LR warm-up epochs")
@click.option("--base-width", default=64)
@click.option("--deferred-bn/--no-deferred-bn", default=True,
              help="DeferredBatchNorm: commit BN running stats once per "
                   "mini-batch so eval-mode statistics match non-pipelined "
                   "training (reference: torchgpipe/batchnorm.py:17-155; the "
                   "transparency claim this benchmark exists to prove)")
@click.option("--bn-refresh", default=0,
              help="post-training BN statistic refresh: run this many "
                   "train-mode forward sweeps with FROZEN params so the "
                   "running stats catch up to the final weights (they lag "
                   "by the 0.9 commit momentum during training), then "
                   "report a final eval-mode top-1.  The standard BN "
                   "re-estimation recipe; makes the eval-side oracle bite "
                   "at meaningful accuracy")
def main(experiment, epochs, data_dir, image, dataset_size, classes, lr,
         warmup_epochs, base_width, deferred_bn, bn_refresh):
    n_stages, batch, chunks = EXPERIMENTS[experiment]
    layers = resnet101(num_classes=classes, base_width=base_width)
    model = build_gpipe(layers, None, n_stages, chunks, "except_last",
                        deferred_batch_norm=deferred_bn)

    X, Y = _dataset(data_dir, dataset_size, image, classes)
    batch = min(batch, X.shape[0])
    in_spec = jax.ShapeDtypeStruct((batch,) + X.shape[1:], X.dtype)
    params, state = model.init(jax.random.PRNGKey(0), in_spec)
    rng = jax.random.PRNGKey(1)
    steps = max(1, X.shape[0] // batch)
    t0 = time.time()
    for epoch in range(epochs):
        # Gradual warm-up LR scaling (reference: Goyal et al. recipe,
        # benchmarks/resnet101-accuracy/main.py:22-93).
        scale = min(1.0, (epoch + 1) / max(1, warmup_epochs))
        epoch_lr = lr * scale * batch / 256
        correct = correct_tr = total = 0
        losses = []
        for step in range(steps):
            lo = (step * batch) % X.shape[0]
            xb = jax.lax.dynamic_slice_in_dim(X, lo, batch, 0)
            yb = jax.lax.dynamic_slice_in_dim(Y, lo, batch, 0)
            key = jax.random.fold_in(rng, epoch * steps + step)
            loss, grads, state, logits_tr = model.value_and_grad(
                params, state, xb, yb, _loss_with_logits, rng=key
            )
            params = tuple(
                jax.tree_util.tree_map(
                    lambda p, g: p - epoch_lr * g, ps, gs
                )
                for ps, gs in zip(params, grads)
            )
            # Two accuracies: train-mode (batch BN statistics — tracks the
            # optimization itself; logits from the training forward, note
            # pre-update params) and eval-mode (running statistics — the
            # DeferredBatchNorm contract; converges to train-mode only once
            # the weights slow down, so short runs read it near the floor).
            out, _ = model.apply(params, state, xb, train=False)
            correct_tr += int(jnp.sum(jnp.argmax(logits_tr, -1) == yb))
            correct += int(jnp.sum(jnp.argmax(out, -1) == yb))
            total += batch
            losses.append(float(loss))
        print(
            f"{hr_time(time.time() - t0)} | {experiment} | epoch {epoch + 1}: "
            f"loss {np.mean(losses):.4f}, "
            f"top-1 {100 * correct / total:.2f}%, "
            f"train-mode top-1 {100 * correct_tr / total:.2f}%",
            flush=True,
        )

    if bn_refresh:
        # BN re-estimation: the running stats are an EMA over commits made
        # while the weights were still moving; sweep the data in train mode
        # with frozen params so every commit reflects the final weights
        # (residual stale fraction decays as 0.9^commits).
        for sweep in range(bn_refresh):
            for step in range(steps):
                lo = (step * batch) % X.shape[0]
                xb = jax.lax.dynamic_slice_in_dim(X, lo, batch, 0)
                # Disjoint from the training-step fold_in stream.
                key = jax.random.fold_in(
                    rng, 1_000_000 + sweep * steps + step
                )
                _, state = model.apply(params, state, xb, rng=key, train=True)
        correct = 0
        for step in range(steps):
            lo = (step * batch) % X.shape[0]
            xb = jax.lax.dynamic_slice_in_dim(X, lo, batch, 0)
            yb = jax.lax.dynamic_slice_in_dim(Y, lo, batch, 0)
            out, _ = model.apply(params, state, xb, train=False)
            correct += int(jnp.sum(jnp.argmax(out, -1) == yb))
        print(
            f"{hr_time(time.time() - t0)} | {experiment} | "
            f"final eval top-1 after {bn_refresh} BN-refresh sweeps: "
            f"{100 * correct / (steps * batch):.2f}%",
            flush=True,
        )


if __name__ == "__main__":
    main()
