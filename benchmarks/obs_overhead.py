"""Telemetry overhead rung: tracer + registry + reporter on vs off.

The obs layer's promise is observability that is ALWAYS ON — which only
holds if recording costs nothing measurable.  This rung times the CPU
tiny-llama training step twice: bare, and fully instrumented (a
``sync=False`` :class:`~torchgpipe_tpu.utils.tracing.Timeline` on the
engine — one ``perf_counter`` pair + list append per cell — plus a
:class:`~torchgpipe_tpu.obs.StepReporter` on a shared
:class:`~torchgpipe_tpu.obs.MetricsRegistry` called once per step).
``sync=False`` deliberately: ``sync=True`` is the *measurement* mode
(it serializes on purpose — that cost is the ablation's point, not
overhead); the always-on production configuration is dispatch
recording.

The two arms run INTERLEAVED (A/B per round) so host frequency drift
hits both equally, and each arm's per-step times are medianed.  Gate:
instrumented / bare − 1 must be **< 2%** (``BENCH_NOTES.md`` records
the measured figure).  Emits one JSON line (the bench contract)::

    env JAX_PLATFORMS=cpu python bench.py --obs-overhead
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Tuple

OVERHEAD_GATE = 0.02  # <2% instrumented-over-bare, the documented bound
CHUNKS = 4
ROUNDS = 12  # per-arm measured steps (interleaved A/B)


def _build(tracer: Any) -> Tuple[Any, Any]:
    import jax
    import jax.numpy as jnp

    from benchmarks.llama_speed import PRESETS
    from torchgpipe_tpu.gpipe import GPipe
    from torchgpipe_tpu.models.transformer import TransformerConfig, llama

    dim, n_layers, n_heads, n_kv, vocab, mlp_ratio = PRESETS["tiny"]
    cfg = TransformerConfig(
        vocab=vocab, dim=dim, n_layers=n_layers, n_heads=n_heads,
        n_kv_heads=n_kv, mlp_ratio=mlp_ratio,
    )
    layers = llama(cfg)
    n_stages = 2
    base, rem = len(layers) // n_stages, len(layers) % n_stages
    balance = [
        base + (1 if j >= n_stages - rem else 0) for j in range(n_stages)
    ]
    model = GPipe(layers, balance=balance, chunks=CHUNKS,
                  checkpoint="except_last", tracer=tracer)
    x = jnp.zeros((8, 128), jnp.int32)
    return model, x


def _stepper(model: Any, x: Any, reporter: Any) -> Callable[[int], float]:
    """Returns ``run(i) -> seconds`` for one blocked training step,
    including the reporter tick when one is attached (that IS the
    instrumented arm's per-step cost)."""
    import jax

    from torchgpipe_tpu.models.transformer import cross_entropy

    def loss_fn(out: Any, tok: Any) -> Any:
        return cross_entropy(out[:, :-1, :], tok[:, 1:])

    in_spec = jax.ShapeDtypeStruct(x.shape, x.dtype)
    params, state = model.init(jax.random.PRNGKey(0), in_spec)
    rng = jax.random.PRNGKey(1)

    def run(i: int) -> float:
        t0 = time.perf_counter()
        loss, grads, _, _ = model.value_and_grad(
            params, state, x, x, loss_fn, rng=jax.random.fold_in(rng, i)
        )
        jax.block_until_ready((loss, grads))
        if reporter is not None:
            reporter.step()
        return time.perf_counter() - t0

    run(0)  # compile warmup, outside the timed rounds
    return run


def run() -> Dict[str, Any]:
    from torchgpipe_tpu.obs import MetricsRegistry, StepReporter
    from torchgpipe_tpu.utils.tracing import Timeline

    bare_model, x = _build(tracer=None)
    tracer = Timeline(sync=False)
    reg = MetricsRegistry()
    reporter = StepReporter(registry=reg, items_per_step=x.shape[0],
                            label="obs-overhead", log_every=0)
    obs_model, _ = _build(tracer=tracer)

    bare = _stepper(bare_model, x, reporter=None)
    inst = _stepper(obs_model, x, reporter=reporter)
    bare_times: List[float] = []
    inst_times: List[float] = []
    for i in range(1, ROUNDS + 1):
        bare_times.append(bare(i))
        inst_times.append(inst(i))
    bare_times.sort()
    inst_times.sort()
    b = bare_times[len(bare_times) // 2]
    o = inst_times[len(inst_times) // 2]
    overhead = o / b - 1.0
    assert tracer.events, "instrumented arm recorded no spans"
    assert reporter.steps == ROUNDS + 1
    return {
        "metric": "obs overhead [tiny llama, cpu, tracer+registry+reporter]",
        "value": round(overhead * 100, 3),
        "unit": "percent",
        "platform": "cpu",
        # Per-step blocking in both arms: neither can over-report.
        "validated": True,
        "gate_percent": OVERHEAD_GATE * 100,
        "pass": overhead < OVERHEAD_GATE,
        "bare_step_ms": round(b * 1e3, 3),
        "instrumented_step_ms": round(o * 1e3, 3),
        "spans_per_step": len(tracer.events) // (ROUNDS + 1),
    }


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    result = run()
    print(json.dumps(result), flush=True)
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
