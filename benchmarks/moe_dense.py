"""MoE vs dense at matched parameters: sparsity's FLOP win, measured.

The ``bench.py --moe`` rung trains TWO tiny llamas with (near-)IDENTICAL
parameter counts through the SAME SpmdGPipe engine on the same token
stream and reports wall-clock tokens/s:

* **moe** — every block's MLP is an E-expert layer (each expert hidden
  ``mlp_ratio * dim``), token-choice top-k routing, ``dropless``
  dispatch (megablocks-style grouped matmuls: per-step FFN work is
  exactly ``k*t`` expert rows regardless of router balance, so the
  measured number is deterministic in shape — no capacity-drop noise);
* **dense** — the classic llama whose single MLP hidden is
  ``n_experts * mlp_ratio * dim``: the SAME total FFN weights as the E
  experts combined (the router's ``[dim, E]`` gate is the only extra,
  reported as ``param_ratio``).

Per token the MoE touches ``top_k / n_experts`` of the FFN weights the
dense model must drag through every matmul, so on a serialized CPU host
(where FLOPs ARE time) real tokens/s must move toward the
``1 / (attn_share + ffn_share * k/E)`` bound.  The benchmark prints the
measured speedup next to that bound; ``--gate`` enforces
``--min-speedup``.  Equivalence is NOT claimed — the two models compute
different functions by design; the exactness story for MoE itself
(ep-sharded vs single-chip) lives in tools/moe_verify.py.

Usage::

    env JAX_PLATFORMS=cpu python bench.py --moe              # CPU ref
    env JAX_PLATFORMS=cpu python -m benchmarks.moe_dense --json
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")


def _n_params(params) -> int:
    return sum(int(a.size) for a in jax.tree_util.tree_leaves(params))


def _expert_params(params, n_experts: int) -> int:
    """Total weights living inside expert stacks: the pipe stacks each
    stage's blocks, so an ``[E, dim, hidden]`` expert weight appears as
    a ``[stages_per_rank*blocks, E, ...]`` 4-d leaf."""
    return sum(
        int(a.size) for a in jax.tree_util.tree_leaves(params)
        if getattr(a, "ndim", 0) == 4 and a.shape[1] == n_experts
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=2)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--topk", type=int, default=2)
    ap.add_argument("--batches", type=int, default=4)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--min-speedup", type=float, default=1.1)
    ap.add_argument("--gate", action="store_true",
                    help="fail (exit 1) when MoE tokens/s misses "
                         "--min-speedup x dense")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line (bench.py --moe)")
    args = ap.parse_args(argv)

    import optax

    from torchgpipe_tpu.models.moe import MoEConfig, llama_moe_spmd
    from torchgpipe_tpu.models.transformer import (
        TransformerConfig,
        cross_entropy,
        llama_spmd,
    )
    from torchgpipe_tpu.spmd import SpmdGPipe, make_mesh

    n = min(args.stages, len(jax.devices()))
    cfg = TransformerConfig(
        vocab=args.vocab, dim=args.dim, n_layers=2 * n, n_heads=4,
        n_kv_heads=2,
    )
    moe = MoEConfig(
        n_experts=args.experts, top_k=args.topk, dispatch="dropless"
    )
    # Matched FFN weights EXACTLY: the gated mlp_hidden rounds
    # ``2/3 * ratio * dim`` up to a 128 multiple, so scaling mlp_ratio
    # by E would not give E x the expert hidden — invert the formula
    # for the dense ratio that lands on ``E * expert_hidden`` (itself a
    # 128 multiple, so the round-up is the identity on it).
    dense_hidden = args.experts * cfg.mlp_hidden
    dense_cfg = dataclasses.replace(
        cfg, mlp_ratio=3.0 * dense_hidden / (2.0 * cfg.dim)
    )

    rng = np.random.RandomState(0)
    batches = [
        (jnp.asarray(rng.randint(0, args.vocab, (args.batch, args.seq)),
                     jnp.int32),
         jnp.asarray(rng.randint(0, args.vocab, (args.batch, args.seq)),
                     jnp.int32))
        for _ in range(args.batches)
    ]
    spec = jax.ShapeDtypeStruct((args.batch, args.seq), jnp.int32)
    mesh = make_mesh(n, devices=jax.devices()[:n])
    opt = optax.sgd(1e-3)

    def rung(parts):
        block, pre, post = parts
        pipe = SpmdGPipe(
            block, n, mesh, chunks=2, loss_fn=cross_entropy,
            pre=pre, post=post, checkpoint="except_last",
        )
        params = pipe.place(pipe.init(jax.random.PRNGKey(0), spec))
        step = pipe.make_train_step(opt, donate=False)
        opt_state = pipe.place_tree(opt.init(params))
        # Warmup (compile) outside the timed window, then stream the
        # whole batch list --repeats times.
        l, p, s = step(params, opt_state, *batches[0])
        jax.block_until_ready(l)
        t0 = time.perf_counter()
        for _ in range(args.repeats):
            for x, y in batches:
                l, p, s = step(p, s, x, y)
        jax.block_until_ready(l)
        dt = time.perf_counter() - t0
        tokens = args.repeats * args.batches * args.batch * args.seq
        return params, float(l), round(tokens / dt, 1)

    moe_params, moe_loss, moe_tok_s = rung(
        llama_moe_spmd(cfg, moe, n)
    )
    dense_params, dense_loss, dense_tok_s = rung(llama_spmd(dense_cfg, n))

    n_moe, n_dense = _n_params(moe_params), _n_params(dense_params)
    experts = _expert_params(moe_params, args.experts)
    active = n_moe - experts + experts * args.topk // args.experts
    out = {
        "bench": "moe_dense",
        "platform": jax.devices()[0].platform,
        "n_experts": args.experts,
        "top_k": args.topk,
        "dispatch": moe.dispatch,
        "moe_params": n_moe,
        "dense_params": n_dense,
        # ~1.0 by construction: the router gate is the only extra.
        "param_ratio": round(n_moe / n_dense, 4),
        "active_params": active,
        "active_fraction": round(active / n_moe, 4),
        "moe_tok_s": moe_tok_s,
        "dense_tok_s": dense_tok_s,
        "speedup": round(moe_tok_s / dense_tok_s, 3),
        "moe_loss": round(moe_loss, 4),
        "dense_loss": round(dense_loss, 4),
    }
    out["speedup_ok"] = out["speedup"] >= args.min_speedup

    if args.json:
        print(json.dumps(out))
    else:
        print(json.dumps(out, indent=2))
    if abs(out["param_ratio"] - 1.0) > 0.02:
        print(f"FAIL: parameter counts not matched "
              f"(ratio {out['param_ratio']})")
        return 1
    if args.gate and not out["speedup_ok"]:
        print(f"FAIL: MoE speedup {out['speedup']} < {args.min_speedup}")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
