"""Zero-bubble vs 1F1B vs fill-drain: measured wall-clock next to the
static schedule models (round-3 verdict ask #5).

The zero-bubble claim in this repo has two layers:

* the STATIC model — ``ZeroBubbleTables.weighted_makespan`` predicts the
  lockstep makespan from per-op costs (parallel/zerobubble.py), and
  ``tests/test_zerobubble.py`` asserts its >=1.2x win over 1F1B;
* the COMPILED program — one scan over ticks whose per-tick overhead the
  static model does not see.

This driver times real ``SpmdGPipe.train_step`` steady-state steps for all
three schedules at ``checkpoint='never'`` (the zero-recompute zb mode —
``checkpoint='always'`` exists too since round 4 — and the
apples-to-apples work profile: no recompute anywhere) and prints them
next to TWO predictions built from per-cell costs calibrated on one
device:

* ``parallel``  — the lockstep makespan with perfect stage overlap (zb:
  ``weighted_makespan(t_f, t_b/2, t_b/2)``; fill-drain/1f1b share the
  uniform-cell figure ``(m + n - 1)(t_f + t_b)``) — what the schedule
  buys on n real chips;
* ``serial``    — ``n * m * (t_f + t_b)``, total work with NO overlap —
  what a single-core host can at best achieve.

On this container (ONE physical core under an 8-virtual-device CPU mesh)
the measured number tracks the SERIAL column: stage "parallelism" is
time-sliced, so the bubble economy physically cannot show in wall-clock
here.  What the run validates is (a) the schedules' total-work parity at
equal checkpoint mode — measured ratios near 1.0 against each other and
against ``serial`` — and (b) the per-tick compiled-scan overhead
(``measured - serial``), the static model's documented blind spot.  The
PARALLEL column is the multi-chip projection those same calibrated costs
imply; the >=1.2x zb-vs-1f1b figure lives there, testable in wall-clock
only on a real multi-chip slice.

Reference anchor: the reference has no schedule-economy driver at all
(its pipeline is fill-drain only; docs/benchmarks.rst measures model
throughput) — this is new surface for the zb/1f1b capability.

Usage::

    env PYTHONPATH=/root/repo JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/zb_timing.py [--stages 4] [--chunks 8] [--steps 5]
"""

from __future__ import annotations

import argparse
import os
import time

import jax
import jax.numpy as jnp

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

from torchgpipe_tpu.layers import chain
from torchgpipe_tpu.ops import dense, gelu, layer_norm
from torchgpipe_tpu.parallel.zerobubble import (
    fused_1f1b_weighted_makespan,
    zero_bubble_tables,
)
from torchgpipe_tpu.spmd import SpmdGPipe, make_mesh


def make_block(dim: int):
    return chain(
        [layer_norm(name="ln"), dense(dim, name="fc1"), gelu("act"),
         dense(dim, name="fc2")],
        name="block",
    )


def mse(out, tgt):
    return jnp.mean((out.astype(jnp.float32) - tgt) ** 2)


def calibrate_cell(block, dim: int, mb: int, iters: int = 30):
    """Median single-device fwd / bwd(dx+dw fused) times for ONE stage
    cell at the pipeline's micro-batch size."""
    dev = jax.devices()[0]
    x = jax.device_put(jnp.ones((mb, dim)), dev)
    params, _ = block.init(jax.random.PRNGKey(0), jax.ShapeDtypeStruct(x.shape, x.dtype))
    params = jax.device_put(params, dev)

    fwd = jax.jit(lambda p, x: block.apply(p, (), x, rng=None, train=True)[0])

    def loss(p, x):
        return jnp.sum(fwd(p, x))

    bwd = jax.jit(jax.grad(loss, argnums=(0, 1)))
    jax.block_until_ready(fwd(params, x))
    jax.block_until_ready(bwd(params, x))

    def med(f):
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(f(params, x))
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2]

    t_fwd = med(lambda p, x: fwd(p, x))
    t_fwdbwd = med(lambda p, x: bwd(p, x))
    return t_fwd, max(t_fwdbwd - t_fwd, 1e-9)


def time_schedule(schedule: str, n: int, m: int, dim: int, batch: int,
                  steps: int, unroll: int = 1, **kw) -> float:
    mesh = make_mesh(n, 1, devices=jax.devices()[:n])
    pipe = SpmdGPipe(
        make_block(dim), n, mesh, chunks=m, loss_fn=mse,
        checkpoint="never", schedule=schedule,
        scan_unroll=True if unroll == 0 else unroll, **kw,
    )
    spec = jax.ShapeDtypeStruct((batch, dim), jnp.float32)
    params = pipe.place(pipe.init(jax.random.PRNGKey(0), spec))
    x = jnp.ones((batch, dim))
    tgt = jnp.zeros((batch, dim))
    jax.block_until_ready(pipe.train_step(params, x, tgt))  # compile
    ts = []
    for _ in range(steps):
        t0 = time.perf_counter()
        jax.block_until_ready(pipe.train_step(params, x, tgt))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--chunks", type=int, default=8)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--mb", type=int, default=8, help="rows per micro-batch")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--unroll", type=int, default=1,
                    help="SpmdGPipe scan_unroll (0 = fully unroll)")
    args = ap.parse_args()
    n, m = args.stages, args.chunks
    batch = args.mb * m
    if len(jax.devices()) < n:
        raise SystemExit(
            f"need {n} devices (run under "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8)"
        )

    block = make_block(args.dim)
    t_f, t_b = calibrate_cell(block, args.dim, args.mb)
    print(f"calibrated per-cell costs (dim={args.dim}, mb={args.mb}): "
          f"t_f={t_f*1e3:.3f} ms, t_b={t_b*1e3:.3f} ms", flush=True)

    tables = zero_bubble_tables(n, m)
    pred_parallel = {
        "fill_drain": (m + n - 1) * (t_f + t_b),
        "1f1b": fused_1f1b_weighted_makespan(n, m, t_f, t_b),
        "zb": tables.weighted_makespan(t_f, t_b / 2, t_b / 2),
    }
    pred_serial = n * m * (t_f + t_b)

    print(f"\n{'schedule':<12} {'measured':>11} {'serial':>11} "
          f"{'parallel':>11} {'meas/serial':>12} {'overhead':>10}")
    measured = {}
    for schedule in ("fill_drain", "1f1b", "zb"):
        dt = time_schedule(schedule, n, m, args.dim, batch, args.steps,
                           unroll=args.unroll)
        measured[schedule] = dt
        over = dt - pred_serial
        print(f"{schedule:<12} {dt*1e3:>9.1f}ms {pred_serial*1e3:>9.1f}ms "
              f"{pred_parallel[schedule]*1e3:>9.1f}ms "
              f"{dt/pred_serial:>12.2f} {over*1e3:>8.1f}ms", flush=True)

    zb_win_pred = pred_parallel["1f1b"] / pred_parallel["zb"]
    canon = (fused_1f1b_weighted_makespan(n, m, 1.0, 2.0)
             / tables.weighted_makespan(1.0, 1.0, 1.0))
    print(f"\nstatic-model zb win over 1f1b (n={n}, m={m}, perfect overlap, "
          f"50/50 B/W split): {zb_win_pred:.2f}x at calibrated costs "
          f"(t_b/t_f={t_b/t_f:.1f}); {canon:.2f}x at the canonical "
          f"MXU profile (t_b = 2 t_f)")
    print("single-core host: measured column tracks 'serial' (no true stage "
          "overlap); 'parallel' is the multi-chip projection from the same "
          "calibrated costs.")
    print("measured zb/1f1b wall-clock ratio here (total-work parity + scan "
          f"overhead only): {measured['1f1b']/measured['zb']:.2f}x")


if __name__ == "__main__":
    main()
