"""AmoebaNet-D (18, 256) speed benchmark — the reference's headline grid.

Reference: benchmarks/amoebanetd-speed/main.py:33-109 — experiments
n∈{2,4,8} × m∈{1,4,32} with hand-tuned batch sizes and balances;
``checkpoint='always'`` when m=1 else ``'except_last'``.  The hand balances
below are re-derived defaults (AmoebaNet cells are heterogeneous; pass
``--balance`` or use ``torchgpipe_tpu.balance`` to retune for your chips).
"""

from __future__ import annotations

import click
import jax
import jax.numpy as jnp

from benchmarks.common import bf16_option, build_gpipe, run_speed, softmax_xent
from torchgpipe_tpu.models import amoebanetd

# name -> (n_stages, batch, chunks, balance, checkpoint); layer count is
# 3 + num_layers + 3 = 24 for num_layers=18 (stem + cells + classify).
EXPERIMENTS = {
    "n1m1": (1, 64, 1, None, "always"),
    "n1m8": (1, 128, 8, None, "except_last"),
    "n2m1": (2, 96, 1, [7, 17], "always"),
    "n2m4": (2, 256, 4, [9, 15], "except_last"),
    "n2m32": (2, 1280, 32, [9, 15], "except_last"),
    "n4m1": (4, 160, 1, [3, 4, 5, 12], "always"),
    "n4m4": (4, 360, 4, [3, 6, 7, 8], "except_last"),
    "n4m32": (4, 1152, 32, [3, 6, 7, 8], "except_last"),
    "n8m1": (8, 196, 1, [2, 2, 2, 2, 2, 3, 4, 7], "always"),
    "n8m4": (8, 480, 4, [2, 2, 2, 3, 3, 4, 4, 4], "except_last"),
    "n8m32": (8, 1280, 32, [2, 2, 2, 3, 3, 4, 4, 4], "except_last"),
}


@click.command()
@click.argument("experiment", type=click.Choice(sorted(EXPERIMENTS)))
@click.option("--epochs", default=3, help="timed epochs (first is warm-up)")
@click.option("--steps", default=10, help="steps per epoch")
@click.option("--num-layers", default=18)
@click.option("--num-filters", default=256)
@click.option("--image", default=224, help="input image size")
@click.option("--batch", default=None, type=int, help="override batch size")
@bf16_option
def main(experiment, epochs, steps, num_layers, num_filters, image, batch, bf16):
    n, bsz, chunks, balance, ckpt = EXPERIMENTS[experiment]
    bsz = batch or bsz
    layers = amoebanetd(
        num_classes=1000, num_layers=num_layers, num_filters=num_filters
    )
    if balance is not None and sum(balance) != len(layers):
        balance = None  # model size changed; fall back to even split
    model = build_gpipe(layers, balance, n, chunks, ckpt, bf16=bf16)
    x = jnp.zeros((bsz, image, image, 3), jnp.float32)
    y = jax.random.randint(jax.random.PRNGKey(0), (bsz,), 0, 1000)
    tput = run_speed(
        model, x, y, softmax_xent,
        epochs=epochs, steps_per_epoch=steps, label=experiment,
    )
    print(f"FINAL | amoebanetd-speed {experiment}: {tput:.1f} samples/sec")


if __name__ == "__main__":
    main()
