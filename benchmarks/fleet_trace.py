"""Fleet rung: a seeded synthetic trace through router + reuse + spec.

The fleet claims — prefix-reuse hit-rate → TTFT drop, speculation
acceptance → TPOT drop, failover that loses nothing — are MEASURED
here, on the production-shaped load :mod:`torchgpipe_tpu.fleet.trace`
generates (ragged lengths, bursty MMPP arrivals, Zipf-skewed
shared-prefix tenants, seeded), never asserted from a hand-picked
burst.  Four rungs serve the SAME trace:

* ``baseline``  — router over 2 plain engines (power-of-two-choices);
* ``prefix``    — 2 ``RadixPrefixCache``-backed replicas;
* ``spec``      — 2 ``SpeculativeEngine`` replicas (trained draft);
* ``failover``  — the baseline fleet with replica r0 killed mid-trace
  (``faults.inject(die_at_step=...)``).

A fifth **telemetry-overhead** rung then gates the request-tracing +
SLO layer (``obs.reqtrace``/``obs.slo``): ONE pre-warmed instrumented
fleet replays the SAME trace with its telemetry toggled ON (per-replica
flight recorders recording rid-threaded request spans + a ticking
``SloMonitor``) and OFF (the production off-switch: the attributes set
to None), in order-alternated gc-hygienic rounds, and the ratio of
median times must stay under the repo's established <2% telemetry
gate, outputs bitwise-identical.  Toggling one fleet rather than
comparing two separately built ones is deliberate: fleet-object
identity (allocator layout, history) measured 2-8% of noise on CPU —
far above the real per-event cost (see BENCH_NOTES round 19).

Measurement contract:

* **Exactness is the hard gate** — all four rungs must emit BITWISE
  identical per-request token streams (greedy decode is replica- and
  path-independent); any divergence exits non-zero, no numbers
  published.
* **No silent caps** — the trace generator's honesty counters
  (``skipped_too_long``, per-tenant counts, shareable fraction) are
  part of the published line; a run that dropped trace segments says
  so in the same JSON object as its wins.
* **Predictable-text regime, declared** — target AND draft are trained
  on the mod-vocab ring task (the ``examples/serve.py`` corpus), and
  trace prompts are mapped onto ring windows (tenant prefixes stay
  shared, suffix starts stay random) so the draft has real signal;
  acceptance is genuinely measured, not forced.  Random-prompt
  acceptance would be ~0 for any small draft — speculation's wins are
  a property of predictable text, and the bench says which regime it
  measures.
* **Latency inside the timed region** — TTFT/TPOT come from the shared
  :class:`~torchgpipe_tpu.serving.metrics.ServingMetrics` (one
  instance across both replicas), whose clocks tick at token-emission
  time; the engine host-fetches every token (streaming), so laziness
  cannot fake a timing.

Usage::

    env JAX_PLATFORMS=cpu python -m benchmarks.fleet_trace
    env JAX_PLATFORMS=cpu python bench.py --fleet      # one JSON line
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import time
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

from torchgpipe_tpu import GPipe, fleet
from torchgpipe_tpu.models import mpmd_params_for_generation
from torchgpipe_tpu.models.transformer import (
    TransformerConfig,
    cross_entropy,
    llama,
)
from torchgpipe_tpu.resilience import faults
from torchgpipe_tpu.serving import Engine, ServingMetrics
from torchgpipe_tpu.serving.engine import Engine as _Engine

VOCAB = 64


def _train(cfg: TransformerConfig, balance: List[int],
           seed: int, steps: int):
    """Train one llama on the mod-vocab ring (the serve-example task):
    rows start every 4 tokens so the batch covers every v -> v+1
    transition — completions become predictable, which is the regime
    speculation exists for."""
    model = GPipe(llama(cfg), balance=balance, chunks=2)
    b, s = 8, 16
    data = jnp.mod(
        jnp.arange(s + 1)[None, :] + (4 * jnp.arange(b))[:, None], VOCAB
    )
    x, y = data[:, :-1], data[:, 1:]
    params, state = model.init(
        jax.random.PRNGKey(seed),
        jax.ShapeDtypeStruct(x.shape, x.dtype),
    )
    loss = None
    for _ in range(steps):
        loss, grads, state, _ = model.value_and_grad(
            params, state, x, y, cross_entropy
        )
        params = tuple(
            jax.tree_util.tree_map(lambda p, g: p - 0.5 * g, ps, gs)
            for ps, gs in zip(params, grads)
        )
    return mpmd_params_for_generation(model, params), float(loss)


def _ring_window(first: int, n: int) -> np.ndarray:
    return np.mod(first + np.arange(n), VOCAB).astype(np.int32)


def _ring_mapped(reqs: List[fleet.TraceRequest]) -> List[Tuple]:
    """Map each trace prompt onto ring windows: the tenant prefix keeps
    its first token (so every request of a tenant still shares the SAME
    prefix — the prefix cache's food) and the suffix keeps its first
    token (so suffixes stay diverse), but both continue along the
    trained ring — in-distribution text the draft can predict."""
    out = []
    for r in reqs:
        pre = _ring_window(int(r.prompt[0]), r.prefix_len)
        suf = _ring_window(
            int(r.prompt[r.prefix_len]), r.prompt.size - r.prefix_len
        )
        out.append((np.concatenate([pre, suf]), r.max_new_tokens,
                    r.session))
    return out


def _program_cache_sizes(engines: Dict[str, _Engine]) -> Dict[str, int]:
    """Per-(replica, program) XLA executable counts — the steady-state
    stability gate reads this before and after the timed region."""
    out: Dict[str, int] = {}
    for name, eng in engines.items():
        for kind, fn in eng._prefill_fns.items():
            out[f"{name}/{kind}"] = fn._cache_size()
        out[f"{name}/decode"] = eng._decode_fn._cache_size()
        if getattr(eng, "_prefix_copy_fn", None) is not None:
            out[f"{name}/prefix_copy"] = eng._prefix_copy_fn._cache_size()
        for kind, fn in getattr(eng, "_draft_fns", {}).items():
            out[f"{name}/{kind}"] = fn._cache_size()
    return out


def _serve(mk_engine, reqs, label: str, *,
           die_at=None, seed: int = 1) -> Dict:
    """One rung: warm the fleet with a FULL untimed pass over the trace
    (every program — including the prefix-copy and draft programs, and
    every XLA layout variant a trained-params cache cycles through —
    compiles outside the timed region), then time the steady-state
    closed-loop replay (submit in arrival order, one router step
    between arrivals, run to idle)."""
    metrics = ServingMetrics()     # ONE instance: fleet-wide latencies
    engines = {n: mk_engine(n, metrics) for n in ("r0", "r1")}
    router = fleet.Router(engines, seed=seed)
    for i, (p, n, sess) in enumerate(reqs):
        router.submit(p, n, rid=f"warm-{label}{i}", session=sess)
        router.step()
    router.run()
    programs_before = _program_cache_sizes(engines)
    fleet_metrics = ServingMetrics()
    for rep in router.replicas.values():
        rep.engine.metrics = fleet_metrics    # timed region only
    # The warmup pass advanced the per-replica step clocks die_at_step
    # keys on; re-zero them so the failover rung's death step means
    # "step within the TIMED region" (mid-trace), not "since router
    # construction" (which would kill r0 at the first timed step).
    router.reset_replica_steps()
    # The speculative counters bind to the WARMUP metrics' registry at
    # engine construction; snapshot them so the published acceptance is
    # the timed region's delta, like every other counter here.
    spec_before = {
        n: (eng._c_proposed.value(), eng._c_accepted.value())
        for n, eng in engines.items() if hasattr(eng, "_c_proposed")
    }
    rids = []
    t0 = time.perf_counter()
    ctx = (
        faults.inject(die_at_step=die_at) if die_at is not None
        else contextlib.nullcontext()
    )
    with ctx:
        for i, (p, n, sess) in enumerate(reqs):
            rids.append(router.submit(
                p, n, rid=f"{label}{i}", session=sess
            ))
            router.step()
        router.run()
    outs = [router.result(r).tolist() for r in rids]
    dt = time.perf_counter() - t0
    toks = sum(len(o) for o in outs)
    snap = fleet_metrics.snapshot()
    acceptance = None
    if spec_before:
        proposed = sum(
            eng._c_proposed.value() - spec_before[n][0]
            for n, eng in engines.items()
        )
        accepted = sum(
            eng._c_accepted.value() - spec_before[n][1]
            for n, eng in engines.items()
        )
        acceptance = accepted / proposed if proposed else 0.0
    return {
        "outs": outs,
        "seconds": dt,
        "tokens": toks,
        "tokens_per_sec": toks / dt,
        "ttft_p50_ms": (snap["ttft_p50"] or 0.0) * 1e3,
        "tpot_p50_ms": (snap["tpot_p50"] or 0.0) * 1e3,
        "prefill_steps": snap["prefill_steps"],
        "decode_steps": snap["decode_steps"],
        "prefix_hits": snap["prefix_hits"],
        "prefix_reused_tokens": snap["prefix_reused_tokens"],
        # pooled timed-region acceptance (None for non-spec rungs)
        "acceptance": acceptance,
        # True iff the timed region compiled NOTHING new: the rung
        # measured the steady state, not a compile.
        "steady_state_stable": (
            die_at is not None      # failover legitimately compiles the
            # survivor's first post-restore shapes; exempt from the gate
            or _program_cache_sizes(engines) == programs_before
        ),
        "router": router,
        "engines": engines,
    }


def _replay(router: "fleet.Router", reqs: List[Tuple],
            label: str) -> Tuple[List[List[int]], float]:
    """One timed closed-loop replay of the trace through a pre-warmed
    fleet (submit in arrival order, one router step between arrivals,
    run to idle) — the telemetry-overhead rung's unit of work."""
    rids = []
    t0 = time.perf_counter()
    for i, (p, n, sess) in enumerate(reqs):
        rids.append(router.submit(p, n, rid=f"{label}{i}", session=sess))
        router.step()
    router.run()
    dt = time.perf_counter() - t0
    return [router.result(r).tolist() for r in rids], dt


def _telemetry_overhead(cfg, params, reqs, common, rounds: int) -> Dict:
    """Toggle-based A/B on ONE fleet: the same instrumented router
    replays the trace with its telemetry armed (per-replica
    FlightRecorders recording rid-threaded request spans + a ticking
    SloMonitor + the router recorder) and disarmed (the attributes set
    to None — the exact production off-switch), in order-alternated
    gc-hygienic rounds.  Sharing one fleet object between A and B is
    the point: two separately built fleets differ by allocator layout
    and object history, and that identity noise measured 2-8% on this
    CPU — far above the real telemetry cost (~1 µs per ring event).
    Ratio of median times, gated <2%."""
    import gc

    from torchgpipe_tpu import obs
    from torchgpipe_tpu.obs.flightrec import FlightRecorder

    shared = obs.MetricsRegistry()
    recorders = {n: FlightRecorder(worker=n) for n in ("r0", "r1")}
    engines = {
        n: Engine(cfg, params, registry=shared.labeled(replica=n),
                  recorder=recorders[n], **common)
        for n in ("r0", "r1")
    }
    # Thresholds far above any CPU latency here: the rung measures the
    # EVALUATION cost (throttled ticks, window math, exact over-
    # threshold counting), not alert handling — no eviction may fire.
    monitor = obs.SloMonitor(
        shared,
        [obs.Objective(name="ttft-p95", threshold=30.0,
                       target=0.95, series="serving_ttft_seconds"),
         obs.Objective(name="tpot-p95", threshold=30.0,
                       target=0.95, series="serving_tpot_seconds")],
        short_window=2.0, long_window=8.0,
    )
    router_rec = FlightRecorder(worker="router")
    router = fleet.Router(
        engines, registry=shared, seed=1, slo=monitor,
        recorder=router_rec,
    )

    def arm(on: bool) -> None:
        for n, rep in router.replicas.items():
            rep.engine.recorder = recorders[n] if on else None
        router.slo = monitor if on else None
        router.recorder = router_rec if on else None

    def timed(label: str) -> Tuple[List[List[int]], float]:
        # One collection BEFORE the timed region, none inside: a GC
        # pause landing in one variant's window is the largest single
        # noise source at this effect size.
        gc.collect()
        gc.disable()
        try:
            return _replay(router, reqs, label)
        finally:
            gc.enable()

    _replay(router, reqs, "tw")     # full warm pass: compiles out
    times_on: List[float] = []
    times_off: List[float] = []
    outs_on = outs_off = None
    for k in range(rounds):
        for phase in (0, 1):
            on = (k % 2 == 0) == (phase == 0)
            arm(on)
            outs, dt = timed(f"{'a' if on else 'b'}{k}-")
            if on:
                outs_on = outs
                times_on.append(dt)
            else:
                outs_off = outs
                times_off.append(dt)
    arm(True)
    if outs_on != outs_off:
        raise SystemExit(
            "EXACTNESS FAIL: telemetry changed an output stream"
        )
    if any(rep.degraded for rep in router.replicas.values()):
        raise SystemExit(
            "telemetry rung evicted a replica — the no-alert "
            "thresholds are wrong"
        )

    from statistics import median

    ratio = median(times_on) / median(times_off)
    ratios = [t / p for t, p in zip(times_on, times_off)]
    return {
        "rounds": rounds,
        "ratio_median": round(ratio, 4),
        "ratio_range": [round(min(ratios), 4), round(max(ratios), 4)],
        "overhead_pct_median": round((ratio - 1.0) * 100.0, 2),
        "within_gate": ratio < 1.02,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=48)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-len", type=int, default=48)
    ap.add_argument("--gamma", type=int, default=3)
    ap.add_argument("--train-steps", type=int, default=100)
    ap.add_argument("--die-at-step", type=int, default=None,
                    help="failover rung's (r0, step); default: "
                    "mid-trace (requests // 2)")
    ap.add_argument("--overhead-rounds", type=int, default=12,
                    help="paired A/B rounds for the telemetry-overhead "
                    "rung (0 disables it); run on an OTHERWISE IDLE "
                    "host — single-round CPU noise exceeds the effect "
                    "(BENCH_NOTES round 19)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line (bench.py --fleet)")
    args = ap.parse_args()

    # Target sized so a decode step is COMPUTE-dominated even on CPU
    # (dim 96 x 4 layers ~ 16x the draft's FLOPs): speculation's TPOT
    # win is target-vs-draft compute, and a dispatch-overhead-bound
    # toy target would hide it behind per-dispatch constants.
    cfg = TransformerConfig(
        vocab=VOCAB, dim=96, n_layers=4, n_heads=4, n_kv_heads=2
    )
    draft_cfg = TransformerConfig(
        vocab=VOCAB, dim=24, n_layers=1, n_heads=2, n_kv_heads=2
    )
    params, loss_t = _train(cfg, [3, 3], seed=0, steps=args.train_steps)
    draft_params, loss_d = _train(
        draft_cfg, [2, 1], seed=1, steps=args.train_steps
    )

    # The trace: shape from the generator, content ring-mapped; the
    # honesty counters ride into the published line.
    tcfg = fleet.TraceConfig(
        n_requests=args.requests, seed=args.seed, vocab=VOCAB,
        max_len=args.max_len, new_tokens=(4, 16),
    )
    stats = fleet.TraceStats()
    reqs = _ring_mapped(list(fleet.synthetic_trace(tcfg, stats)))

    common = dict(num_slots=args.slots, max_len=args.max_len,
                  prefill_chunk=8)

    def plain(name, metrics):
        return Engine(cfg, params, metrics=metrics, **common)

    def prefixed(name, metrics):
        return Engine(
            cfg, params, metrics=metrics,
            prefix_cache=fleet.RadixPrefixCache(min_prefix_len=4,
                                                max_entries=2),
            **common,
        )

    def speculative(name, metrics):
        return fleet.SpeculativeEngine(
            cfg, params, draft_cfg, draft_params, gamma=args.gamma,
            metrics=metrics, **common,
        )

    die_step = (
        args.die_at_step if args.die_at_step is not None
        else args.requests // 2
    )
    rungs = {
        "baseline": _serve(plain, reqs, "b"),
        "prefix": _serve(prefixed, reqs, "p"),
        "spec": _serve(speculative, reqs, "s"),
        "failover": _serve(plain, reqs, "f", die_at=(0, die_step)),
    }

    # HARD GATE 1: bitwise equality across every rung.
    base_outs = rungs["baseline"]["outs"]
    for name, r in rungs.items():
        if r["outs"] != base_outs:
            bad = next(
                i for i, (a, b) in enumerate(zip(r["outs"], base_outs))
                if a != b
            )
            raise SystemExit(
                f"EXACTNESS FAIL: rung {name!r} diverged from baseline "
                f"at request {bad}: {r['outs'][bad]} vs {base_outs[bad]}"
            )

    # HARD GATE 2: the rungs actually exercised their mechanisms
    # (counters below cover the TIMED pass only — warmup has its own
    # ServingMetrics).
    pref = rungs["prefix"]
    hits = pref["prefix_hits"]
    reused = pref["prefix_reused_tokens"]
    if hits < 1:
        raise SystemExit("prefix rung never hit the cache — the trace "
                         "lost its shared prefixes")
    if not pref["prefill_steps"] < rungs["baseline"]["prefill_steps"]:
        raise SystemExit(
            "prefix reuse did not reduce prefill dispatches "
            f"({pref['prefill_steps']} vs "
            f"{rungs['baseline']['prefill_steps']})"
        )
    for rep in pref["router"].replicas.values():
        rep.engine.pool.check_refcounts()
    acceptance = float(rungs["spec"]["acceptance"])
    if acceptance <= 0.0:
        raise SystemExit("speculation accepted nothing — the draft "
                         "carries no signal on this trace")
    fo = rungs["failover"]["router"]
    if fo._c_failovers.value() != 1 or fo._c_moved.value() < 1:
        raise SystemExit(
            f"failover rung did not fail over (failovers="
            f"{fo._c_failovers.value()}, moved={fo._c_moved.value()})"
        )

    # HARD GATE 3: request tracing + SLO evaluation must stay within
    # the repo's established <2% telemetry-overhead budget.
    telemetry = None
    if args.overhead_rounds > 0:
        telemetry = _telemetry_overhead(
            cfg, params, reqs, common, args.overhead_rounds
        )
        if not telemetry["within_gate"]:
            raise SystemExit(
                f"telemetry overhead {telemetry['overhead_pct_median']:+.2f}% "
                f"(median of {telemetry['rounds']} paired rounds, range "
                f"{telemetry['ratio_range']}) exceeds the 2% gate"
            )

    base, px, sp, fv = (
        rungs["baseline"], rungs["prefix"], rungs["spec"],
        rungs["failover"],
    )
    out = {
        "bench": "fleet-trace",
        "platform": jax.devices()[0].platform,
        "requests": args.requests,
        "seed": args.seed,
        "slots_per_replica": args.slots,
        "replicas": 2,
        "train_loss": {"target": round(loss_t, 4),
                       "draft": round(loss_d, 4)},
        # honesty counters: the trace as generated, drops included
        "trace": {
            "generated": stats.generated,
            "skipped_too_long": stats.skipped_too_long,
            "shareable_fraction": round(stats.shareable_fraction, 3),
            "burst_arrivals": stats.burst_arrivals,
            "per_tenant": {
                str(k): v for k, v in sorted(stats.per_tenant.items())
            },
        },
        "baseline": _pub(base),
        "prefix": {
            **_pub(px),
            "hits": int(hits),
            "reused_tokens": int(reused),
            "hit_rate": round(hits / max(stats.generated, 1), 3),
        },
        "spec": {
            **_pub(sp),
            "gamma": args.gamma,
            "acceptance": round(acceptance, 3),
        },
        "failover": {
            **_pub(fv),
            "moved_requests": int(fv["router"]._c_moved.value()),
            "overhead_seconds": round(
                fv["seconds"] - base["seconds"], 4
            ),
        },
        "speedups": {
            "prefix_ttft": round(
                base["ttft_p50_ms"] / max(px["ttft_p50_ms"], 1e-9), 3
            ),
            "spec_tpot": round(
                base["tpot_p50_ms"] / max(sp["tpot_p50_ms"], 1e-9), 3
            ),
            "spec_tokens_per_sec": round(
                sp["tokens_per_sec"] / max(base["tokens_per_sec"],
                                           1e-9), 3
            ),
        },
        "telemetry_overhead": telemetry,
        "exactness_gated": True,
        # every non-failover rung's timed region compiled nothing new
        "steady_state_stable": {
            name: r["steady_state_stable"] for name, r in rungs.items()
        },
        "validated": all(
            r["steady_state_stable"] for r in rungs.values()
        ) and (telemetry is None or telemetry["within_gate"]),
    }
    if args.json:
        print(json.dumps(out), flush=True)
        return
    print(
        f"fleet-trace: {stats.generated} requests "
        f"({stats.skipped_too_long} skipped-too-long, logged), "
        f"2 replicas x {args.slots} slots\n"
        f"  baseline  {base['tokens_per_sec']:8.1f} tok/s  "
        f"ttft {base['ttft_p50_ms']:6.1f}ms  "
        f"tpot {base['tpot_p50_ms']:5.2f}ms  "
        f"prefill {base['prefill_steps']}\n"
        f"  prefix    {px['tokens_per_sec']:8.1f} tok/s  "
        f"ttft {px['ttft_p50_ms']:6.1f}ms  "
        f"tpot {px['tpot_p50_ms']:5.2f}ms  "
        f"prefill {px['prefill_steps']} "
        f"(hit rate {out['prefix']['hit_rate']:.0%}, "
        f"{reused} tokens reused)\n"
        f"  spec      {sp['tokens_per_sec']:8.1f} tok/s  "
        f"ttft {sp['ttft_p50_ms']:6.1f}ms  "
        f"tpot {sp['tpot_p50_ms']:5.2f}ms  "
        f"(acceptance {acceptance:.0%} at gamma={args.gamma})\n"
        f"  failover  {fv['tokens_per_sec']:8.1f} tok/s  "
        f"moved {out['failover']['moved_requests']} requests, "
        f"overhead {out['failover']['overhead_seconds']:+.3f}s\n"
        f"  all rungs bitwise-identical outputs; "
        f"ttft x{out['speedups']['prefix_ttft']:.2f} (prefix), "
        f"tpot x{out['speedups']['spec_tpot']:.2f} / "
        f"throughput x{out['speedups']['spec_tokens_per_sec']:.2f} "
        f"(spec)"
        + (
            f"\n  telemetry  {telemetry['overhead_pct_median']:+.2f}% "
            f"median overhead over {telemetry['rounds']} paired rounds "
            f"(range {telemetry['ratio_range']}) — "
            f"{'within' if telemetry['within_gate'] else 'OVER'} the "
            f"2% gate"
            if telemetry is not None else ""
        ),
        flush=True,
    )


def _pub(r: Dict) -> Dict:
    return {
        "tokens_per_sec": round(r["tokens_per_sec"], 1),
        "seconds": round(r["seconds"], 4),
        "tokens": r["tokens"],
        "ttft_p50_ms": round(r["ttft_p50_ms"], 2),
        "tpot_p50_ms": round(r["tpot_p50_ms"], 3),
        "prefill_steps": r["prefill_steps"],
        "decode_steps": r["decode_steps"],
    }


if __name__ == "__main__":
    main()
