"""Rollout rung: live weight rollouts under a mixed-tier MMPP trace.

PR 20's serving claim — a fleet can roll fresh weights replica-by-
replica THROUGH live traffic, survive a forced rollback, and the
interactive tier never notices — is MEASURED here.  Two rungs serve
the SAME tenant-tiered MMPP trace (tenant 0 → interactive, tenant 1 →
standard, tenants 2/3 → batch) on a 2-replica fleet with one shared
:class:`~torchgpipe_tpu.serving.qos.QosPolicy`:

* ``control`` — no rollout machinery touches the timed region;
* ``rollout`` — the :class:`~torchgpipe_tpu.fleet.rollout.
  RolloutController` completes TWO full rolling updates (v2, v3)
  mid-trace, then a third publish (v4) is force-rolled-back the
  moment the fleet is version-split — the operator "bad vibes" drill.

Every published version carries BIT-IDENTICAL param values, on
purpose: the bitwise gate then isolates the rollout *machinery*
(drain, swap, readmit, resubmit) — any divergence is a scheduling or
state-handoff bug, never a weights delta hiding it.

Measurement contract:

* **Zero drops is the hard gate** — every stream in both rungs must
  finish at its full token budget; a rollout that shed load exits
  non-zero, no numbers published.
* **Exactness is the hard gate** — the rollout rung's per-request
  streams must be BITWISE the control rung's.
* **The headline gate is the QoS claim** — interactive-tier TPOT p95
  (per-replica STEP clock, 1.0 per productive engine step —
  deterministic, host-speed-free) must stay within ``--margin``
  (default 1.1x) of the no-rollout control through two rollouts and
  the rollback.
* **The timed region is compile-free** — a warm pass (which also runs
  one untimed rollout, so the drain→swap→resubmit path compiles
  outside the window) precedes it; every program's trace count must
  be unchanged afterwards.
* **Honesty counters ride along** — the drill must actually witness a
  version-split fleet before rolling back, ``rollout_rollbacks_total``
  must be exactly 1, the fleet must end on v3, the generator's
  ``skipped_too_long`` must be 0, and each rung must produce enough
  interactive TPOT samples for the p95 to mean anything.

Usage::

    env JAX_PLATFORMS=cpu python -m benchmarks.rollout_trace
    env JAX_PLATFORMS=cpu python bench.py --rollout    # one JSON line
"""

from __future__ import annotations

import argparse
import json
import os
import time
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

from torchgpipe_tpu import fleet
from torchgpipe_tpu.layers import sequential_init
from torchgpipe_tpu.models.transformer import TransformerConfig, llama
from torchgpipe_tpu.obs import MetricsRegistry
from torchgpipe_tpu.serving import Engine, QosConfig, QosPolicy, ServingMetrics

VOCAB = 64
MAX_LEN = 48
TIER_OF_TENANT = {0: "interactive", 1: "standard", 2: "batch", 3: "batch"}


class _StepClock:
    """A per-replica virtual clock: t advances 1.0 per productive step
    of the engine it is attached to."""

    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def _make_trace(args: argparse.Namespace) -> Tuple[
    List[fleet.TraceRequest], fleet.TraceStats
]:
    stats = fleet.TraceStats()
    cfg = fleet.TraceConfig(
        n_requests=args.requests, seed=args.seed, vocab=VOCAB,
        max_len=MAX_LEN, n_tenants=4,
    )
    return list(fleet.synthetic_trace(cfg, stats)), stats


def _run_rung(cfg: TransformerConfig, flat: Any,
              reqs: List[fleet.TraceRequest], *,
              rollout: bool, slots: int, seed: int) -> Dict[str, Any]:
    """One rung: build the QoS fleet, warm it with a full untimed pass
    (the rollout rung also completes one untimed v0→v1 rolling update,
    compiling the drain→swap→resubmit path outside the timed region),
    swap in fresh step-clock metrics, replay with the rollout schedule.
    """
    reg = MetricsRegistry()
    pol = QosPolicy(QosConfig(), registry=reg)
    warm_metrics = ServingMetrics()
    engines = {
        name: Engine(cfg, flat, num_slots=slots, max_len=MAX_LEN,
                     prefill_chunk=8, qos=pol, metrics=warm_metrics,
                     registry=reg.labeled(replica=name))
        for name in ("r0", "r1")
    }
    router = fleet.Router(engines, registry=reg, seed=seed)
    ctl = fleet.RolloutController(router) if rollout else None
    for i, req in enumerate(reqs):
        router.submit(req.prompt, req.max_new_tokens, rid=f"warm-{i}",
                      session=req.session,
                      tier=TIER_OF_TENANT[req.tenant],
                      tenant=f"t{req.tenant}")
        router.step()
        if ctl is not None:
            if i == len(reqs) // 2:
                ctl.publish(flat, 1)
            ctl.tick()
    while router.run() != "idle":
        pass
    if ctl is not None:
        while ctl.baseline != 1 or ctl._pending():
            router.step()
            ctl.tick()
        while router.run() != "idle":
            pass

    # Per-replica step clocks + fresh metrics: the timed region's TPOT
    # is engine-steps-per-token, deterministic across hosts.
    clocks: Dict[str, _StepClock] = {}
    for name, rep in router.replicas.items():
        clock = clocks[name] = _StepClock()
        rep.engine.metrics = ServingMetrics(clock=clock)

        def stepper(orig=rep.engine.step, c=clock):
            ran = orig()
            if ran:
                c.t += 1.0
            return ran

        rep.engine.step = stepper
    warm_traces = {
        name: dict(rep.engine.trace_counts)
        for name, rep in router.replicas.items()
    }

    n = len(reqs)
    # Two full rolling updates land mid-trace; the third publish is
    # the rollback drill, late enough that traffic is still flowing.
    publish_at = {n // 6: 2, n // 2: 3, (3 * n) // 4: 4}
    rids: List[str] = []
    events: List[Tuple[float, str]] = []
    awaiting_drill = False
    t0 = time.perf_counter()
    for i, req in enumerate(reqs):
        rids.append(router.submit(
            req.prompt, req.max_new_tokens, rid=f"q{i}",
            session=req.session, tier=TIER_OF_TENANT[req.tenant],
            tenant=f"t{req.tenant}"))
        router.step()
        if ctl is not None:
            version = publish_at.get(i)
            if version is not None:
                ctl.publish(flat, version)
                events.append((i, f"publish:v{version}"))
                awaiting_drill = version == 4
            act = ctl.tick()
            if act and act.startswith(("swap", "rollback", "complete")):
                events.append((i, act))
            # The drill: the moment the fleet is version-split on v4,
            # the operator pulls the cord.
            if awaiting_drill and len(set(ctl.versions().values())) == 2:
                events.append((i, ctl.rollback("forced drill")))
                awaiting_drill = False
    for _ in range(10_000):
        router.step()
        if ctl is not None:
            act = ctl.tick()
            if act and act.startswith(("swap", "rollback", "complete")):
                events.append((n, act))
        if router.idle and (
                ctl is None
                or (ctl.baseline == ctl.target and not ctl._pending())):
            break
    while router.run() != "idle":
        pass
    dt = time.perf_counter() - t0

    for name, rep in router.replicas.items():
        if dict(rep.engine.trace_counts) != warm_traces[name]:
            raise SystemExit(
                f"COMPILE-FREE FAIL: replica {name} traced a program "
                f"inside the timed region: {dict(rep.engine.trace_counts)}"
                f" vs warm {warm_traces[name]}"
            )

    outs = [router.result(r).tolist() for r in rids]
    dropped = [
        rids[i] for i, req in enumerate(reqs)
        if len(outs[i]) != req.max_new_tokens
    ]
    if dropped:
        raise SystemExit(
            f"ZERO-DROP FAIL ({'rollout' if rollout else 'control'} "
            f"rung): {len(dropped)} stream(s) short of budget: "
            f"{dropped[:5]}"
        )

    # Interactive-tier TPOT, step units: a request's decode gap lives
    # on the replica that finished its stream (migrated streams appear
    # on several replicas; only the finishing record counts).
    interactive = {
        f"q{i}" for i, req in enumerate(reqs)
        if TIER_OF_TENANT[req.tenant] == "interactive"
    }
    tpots = [
        r.tpot
        for rep in router.replicas.values()
        for rid, r in rep.engine.metrics.requests.items()
        if (rid in interactive and r.status == "finished"
            and r.tpot is not None)
    ]
    if len(tpots) < 8:
        raise SystemExit(
            f"only {len(tpots)} interactive TPOT samples — the p95 "
            "would be noise; raise --requests or pick another seed"
        )
    toks = sum(len(o) for o in outs)
    out = {
        "outs": outs,
        "seconds": dt,
        "tokens": toks,
        "tokens_per_sec": toks / dt,
        "interactive_tpot_p50": float(np.percentile(tpots, 50)),
        "interactive_tpot_p95": float(np.percentile(tpots, 95)),
        "interactive_samples": len(tpots),
        "steps": {nm: c.t for nm, c in clocks.items()},
        "preemptions": int(pol._c_preemptions.value()),
    }
    if ctl is not None:
        out["events"] = [f"{i}:{e}" for i, e in events]
        out["versions"] = ctl.versions()
        out["rollbacks"] = int(
            reg.get("rollout_rollbacks_total").value()
        )
        out["swaps"] = {
            name: int(reg.get("rollout_swaps_total")
                      .value(replica=name))
            for name in router.replicas
        }
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=48)
    ap.add_argument("--seed", type=int, default=5)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--margin", type=float, default=1.1,
                    help="rollout-rung interactive TPOT p95 must stay "
                    "within this factor of the no-rollout control — "
                    "the 'interactive tier never notices' claim")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line (bench.py --rollout)")
    args = ap.parse_args()

    cfg = TransformerConfig(
        vocab=VOCAB, dim=64, n_layers=2, n_heads=4, n_kv_heads=2
    )
    flat, _, _ = sequential_init(
        llama(cfg), jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((2, 8), jnp.int32),
    )
    reqs, stats = _make_trace(args)
    if stats.skipped_too_long:
        raise SystemExit(
            f"trace generator skipped {stats.skipped_too_long} "
            f"requests — the mix must fit max_len={MAX_LEN}"
        )

    control = _run_rung(cfg, flat, reqs, rollout=False,
                        slots=args.slots, seed=args.seed)
    rollout = _run_rung(cfg, flat, reqs, rollout=True,
                        slots=args.slots, seed=args.seed)

    # HARD GATE 1: bitwise equality — two rollouts and a rollback
    # change nothing in any output stream.
    if rollout["outs"] != control["outs"]:
        bad = next(
            i for i, (a, b) in enumerate(zip(rollout["outs"],
                                             control["outs"]))
            if a != b
        )
        raise SystemExit(
            f"EXACTNESS FAIL: rollout rung diverged from control at "
            f"request {bad}: {rollout['outs'][bad]} vs "
            f"{control['outs'][bad]}"
        )

    # HARD GATE 2: the schedule actually happened — two completed
    # rollouts, one forced rollback, fleet ends on v3.
    if rollout["rollbacks"] != 1:
        raise SystemExit(
            f"rollout rung recorded {rollout['rollbacks']} rollbacks "
            "(want exactly 1) — the drill never fired"
        )
    if rollout["versions"] != {"r0": 3, "r1": 3}:
        raise SystemExit(
            f"fleet did not end on v3: {rollout['versions']} — the "
            "rollback drill did not converge"
        )
    if not any(":rollback" in e for e in rollout["events"]):
        raise SystemExit("no rollback event in the timed region")

    # HARD GATE 3 (headline): interactive-tier TPOT p95 holds within
    # the margin through two rollouts and the rollback.
    ceiling = args.margin * control["interactive_tpot_p95"]
    if rollout["interactive_tpot_p95"] > ceiling + 1e-9:
        raise SystemExit(
            f"QOS FAIL: rollout interactive TPOT p95 "
            f"{rollout['interactive_tpot_p95']:.3f} steps/token vs "
            f"control {control['interactive_tpot_p95']:.3f} x margin "
            f"{args.margin} — the rollout was not invisible to the "
            "interactive tier"
        )

    tiers = {t: 0 for t in ("interactive", "standard", "batch")}
    for req in reqs:
        tiers[TIER_OF_TENANT[req.tenant]] += 1
    out = {
        "bench": "rollout-trace",
        "platform": jax.devices()[0].platform,
        "requests": args.requests,
        "seed": args.seed,
        "slots_per_replica": args.slots,
        "replicas": 2,
        "tier_mix": tiers,
        "trace": {
            "generated": stats.generated,
            "skipped_too_long": stats.skipped_too_long,
            "burst_arrivals": stats.burst_arrivals,
        },
        "control": _pub(control),
        "rollout": {
            **_pub(rollout),
            "events": rollout["events"],
            "versions": rollout["versions"],
            "rollbacks": rollout["rollbacks"],
            "swaps": rollout["swaps"],
        },
        "qos": {
            "control_interactive_tpot_p95": round(
                control["interactive_tpot_p95"], 3
            ),
            "rollout_interactive_tpot_p95": round(
                rollout["interactive_tpot_p95"], 3
            ),
            "margin": args.margin,
            "held": True,
        },
        "zero_drops": True,
        "exactness_gated": True,
        "validated": True,
    }
    if args.json:
        print(json.dumps(out), flush=True)
        return
    print(
        f"rollout-trace: {stats.generated} requests "
        f"(tiers {tiers}) at 2 replicas x {args.slots} slots\n"
        f"  control  interactive tpot "
        f"{control['interactive_tpot_p50']:.3f}/"
        f"{control['interactive_tpot_p95']:.3f} steps p50/p95  "
        f"{control['tokens_per_sec']:8.1f} tok/s wall\n"
        f"  rollout  interactive tpot "
        f"{rollout['interactive_tpot_p50']:.3f}/"
        f"{rollout['interactive_tpot_p95']:.3f} steps p50/p95  "
        f"{rollout['tokens_per_sec']:8.1f} tok/s wall  "
        f"({sum(rollout['swaps'].values())} swaps, "
        f"{rollout['rollbacks']} rollback)\n"
        f"  events: {' '.join(rollout['events'])}\n"
        f"  two rollouts + forced rollback served mid-trace: zero "
        f"drops, streams bitwise vs control, interactive p95 within "
        f"{args.margin}x",
        flush=True,
    )


def _pub(r: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "tokens_per_sec": round(r["tokens_per_sec"], 1),
        "seconds": round(r["seconds"], 4),
        "tokens": r["tokens"],
        "interactive_tpot_p50": round(r["interactive_tpot_p50"], 3),
        "interactive_tpot_p95": round(r["interactive_tpot_p95"], 3),
        "interactive_samples": r["interactive_samples"],
        "preemptions": r["preemptions"],
        "steps": r["steps"],
    }


if __name__ == "__main__":
    main()
