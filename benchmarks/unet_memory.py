"""U-Net memory benchmark: grow (num_convs B, base_channels C) with the
pipeline and report parameter count + per-device peak memory.

Reference: benchmarks/unet-memory/main.py:19-87 — the model grows with the
partition count to show pipeline+checkpointing memory scaling
(docs/benchmarks.rst:41-49: 15.82B params on pipeline-8 vs 362.2M baseline).
"""

from __future__ import annotations

import click
import jax.numpy as jnp

from benchmarks.common import build_gpipe, mse, run_memory
from torchgpipe_tpu.models import unet

# name -> (n_stages, (num_convs B, base_channels C))
EXPERIMENTS = {
    "baseline": (1, (6, 72)),
    "pipeline-1": (1, (11, 128)),
    "pipeline-2": (2, (24, 128)),
    "pipeline-4": (4, (24, 160)),
    "pipeline-8": (8, (48, 160)),
}


@click.command()
@click.argument("experiment", type=click.Choice(sorted(EXPERIMENTS)))
@click.option("--image", default=192)
@click.option("--batch", default=32)
@click.option("--chunks", default=4)
@click.option("--depth", default=5)
@click.option("--num-convs", default=None, type=int, help="override grid B")
@click.option("--base-channels", default=None, type=int, help="override grid C")
def main(experiment, image, batch, chunks, depth, num_convs, base_channels):
    n, (convs, channels) = EXPERIMENTS[experiment]
    convs = num_convs or convs
    channels = base_channels or channels
    layers = unet(
        depth=depth, num_convs=convs, base_channels=channels, output_channels=1
    )
    model = build_gpipe(layers, None, n, chunks, "always")
    x = jnp.zeros((batch, image, image, 3), jnp.float32)
    y = jnp.zeros((batch, image, image, 1), jnp.float32)
    run_memory(model, x, y, mse, label=f"unet-memory {experiment} B={convs} C={channels}")


if __name__ == "__main__":
    main()
