"""AmoebaNet-D memory benchmark: grow (num_layers L, num_filters D) with the
pipeline and report parameter count + per-device peak memory.

Reference: benchmarks/amoebanetd-memory/main.py:20-84
(docs/benchmarks.rst:69-83: (72, 512) = 1.84B params on pipeline-8).
"""

from __future__ import annotations

import click
import jax
import jax.numpy as jnp

from benchmarks.common import build_gpipe, run_memory, softmax_xent
from torchgpipe_tpu.models import amoebanetd

# name -> (n_stages, (num_layers L, num_filters D))
EXPERIMENTS = {
    "baseline": (1, (18, 208)),
    "pipeline-1": (1, (18, 416)),
    "pipeline-2": (2, (18, 544)),
    "pipeline-4": (4, (36, 544)),
    "pipeline-8": (8, (72, 512)),
}


@click.command()
@click.argument("experiment", type=click.Choice(sorted(EXPERIMENTS)))
@click.option("--image", default=224)
@click.option("--batch", default=32)
@click.option("--chunks", default=4)
def main(experiment, image, batch, chunks):
    n, (num_layers, num_filters) = EXPERIMENTS[experiment]
    layers = amoebanetd(
        num_classes=1000, num_layers=num_layers, num_filters=num_filters
    )
    model = build_gpipe(layers, None, n, chunks, "always")
    x = jnp.zeros((batch, image, image, 3), jnp.float32)
    y = jax.random.randint(jax.random.PRNGKey(0), (batch,), 0, 1000)
    run_memory(
        model, x, y, softmax_xent,
        label=f"amoebanetd-memory {experiment} L={num_layers} D={num_filters}",
    )


if __name__ == "__main__":
    main()
