"""Elastic rung: the SLO-priced autoscaler vs a static peak fleet.

PR 17's serving claim — an autoscaled fleet rides a bursty MMPP trace
with LESS provisioned capacity than static peak provisioning, while
holding the declared latency objectives and never dropping in-flight
work — is MEASURED here on the same seeded synthetic trace the fleet
bench uses.  Two rungs serve the SAME trace:

* ``static``     — 2 replicas in rotation for the whole trace (peak
  provisioning: capacity sized for the burst, paid for in the calm);
* ``autoscaled`` — the same 2-replica fleet under
  :class:`~torchgpipe_tpu.fleet.autoscaler.Autoscaler` (Little's-law
  pricing at the declared per-request service time, hysteresis,
  floor 1), which parks a replica in the calm and re-opens it when the
  burst arrives.

Measurement contract:

* **Exactness is the hard gate** — both rungs must emit BITWISE
  identical per-request token streams (greedy decode is replica- and
  scale-event-independent); any divergence exits non-zero, no numbers
  published.  This is the "never drops an in-flight request" claim:
  scale-down rides the router's drain path, so a parked replica's
  live requests finish on the survivor with identical tokens.
* **Capacity is priced in trace time** — ``replica_seconds`` is the
  integral of the in-rotation replica count over the trace's VIRTUAL
  arrival clock (the clock the autoscaler's rate windows read), so the
  published saving is a property of the trace + policy, deterministic
  across runs.  The static rung's integral is by construction
  ``2 x trace duration`` — the peak-provisioned bill.
* **The SLO gate is the steady-state objective** — per-token latency
  (TPOT p95, wall clock, from the shared
  :class:`~torchgpipe_tpu.serving.metrics.ServingMetrics`) must stay
  under the declared objective on the AUTOSCALED rung: scaling to the
  floor may queue work but must not degrade the per-token service
  rate.  TTFT for both rungs is published for comparison (a compressed
  replay queues both rungs artificially, so TTFT is reported, not
  gated).
* **The fleet must actually breathe** — at least one scale-down AND
  one scale-up must occur, and the trajectory may never fall below
  the floor; a trace too calm (or a policy too damped) to exercise
  both directions fails rather than publishing a vacuous saving.

Usage::

    env JAX_PLATFORMS=cpu python -m benchmarks.elastic_autoscale
    env JAX_PLATFORMS=cpu python bench.py --elastic    # one JSON line
"""

from __future__ import annotations

import argparse
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

from torchgpipe_tpu import fleet
from torchgpipe_tpu.layers import sequential_init
from torchgpipe_tpu.models.transformer import TransformerConfig, llama
from torchgpipe_tpu.obs import MetricsRegistry
from torchgpipe_tpu.serving import Engine, ServingMetrics

VOCAB = 64


def _make_trace(args: argparse.Namespace) -> Tuple[
    List[fleet.TraceRequest], fleet.TraceStats
]:
    """The bursty MMPP trace both rungs serve: calm ~20 req/s, bursts
    >100 req/s — the regime where static provisioning pays for the
    burst all trace long."""
    stats = fleet.TraceStats()
    cfg = fleet.TraceConfig(
        n_requests=args.requests, seed=args.seed, vocab=VOCAB,
        max_len=24, new_tokens=(2, 6),
        calm_gap_s=0.05, burst_gap_s=0.002,
        p_enter_burst=0.2, p_exit_burst=0.2,
    )
    return list(fleet.synthetic_trace(cfg, stats)), stats


def _run_fleet(cfg: TransformerConfig, flat: Any,
               reqs: List[fleet.TraceRequest], *,
               autoscale: bool, slots: int,
               service_time_s: float) -> Dict[str, Any]:
    """One rung: warm the fleet with a full untimed pass (every program
    compiles outside the timed region), then replay the trace in
    arrival order — virtual clock driving the autoscaler's rate
    windows, wall clock driving the latency metrics."""
    clock_t = [0.0]
    reg = MetricsRegistry(clock=lambda: clock_t[0])
    warm_metrics = ServingMetrics()
    engines = {
        n: Engine(cfg, flat, num_slots=slots, max_len=32,
                  prefill_chunk=8, metrics=warm_metrics,
                  registry=reg.labeled(replica=n))
        for n in ("r0", "r1")
    }
    router = fleet.Router(engines, registry=reg, seed=0)
    for i, req in enumerate(reqs):
        clock_t[0] = req.arrival_s
        router.submit(req.prompt, req.max_new_tokens,
                      rid=f"warm-{i}", session=req.session)
        router.step()
    while router.run() != "idle":
        pass

    metrics = ServingMetrics()                 # timed region only
    for rep in router.replicas.values():
        rep.engine.metrics = metrics
    scaler = None
    if autoscale:
        # Priced so the calm rate fits one replica's slots and the
        # burst demands the second (same pricing the elastic-verify
        # gate pins).
        scaler = fleet.Autoscaler(
            router, service_time_s=service_time_s, headroom=1.0,
            window_s=0.05, hold_ticks=2, min_replicas=1,
        )

    rids: List[str] = []
    trajectory: List[int] = []
    actions: List[str] = []
    replica_seconds = 0.0
    cap = sum(1 for r in router.replicas.values() if r.in_rotation)
    prev_t: Optional[float] = None
    t0 = time.perf_counter()
    for i, req in enumerate(reqs):
        t = req.arrival_s
        if prev_t is not None:
            replica_seconds += cap * (t - prev_t)
        prev_t = t
        clock_t[0] = t
        if scaler is not None:
            scaler.observe_arrival(1)
        rids.append(router.submit(req.prompt, req.max_new_tokens,
                                  rid=f"q{i}", session=req.session))
        router.step()
        if scaler is not None:
            act = scaler.tick()
            if act is not None:
                actions.append(act)
        cap = sum(1 for r in router.replicas.values() if r.in_rotation)
        trajectory.append(cap)
    while router.run() != "idle":
        pass
    dt = time.perf_counter() - t0

    outs = [router.result(r).tolist() for r in rids]
    snap = metrics.snapshot()
    toks = sum(len(o) for o in outs)
    return {
        "outs": outs,
        "seconds": dt,
        "tokens": toks,
        "tokens_per_sec": toks / dt,
        "ttft_p50_ms": (snap["ttft_p50"] or 0.0) * 1e3,
        "ttft_p95_ms": (snap["ttft_p95"] or 0.0) * 1e3,
        "tpot_p50_ms": (snap["tpot_p50"] or 0.0) * 1e3,
        "tpot_p95_ms": (snap["tpot_p95"] or 0.0) * 1e3,
        "replica_seconds": replica_seconds,
        "trajectory": trajectory,
        "actions": actions,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=40)
    ap.add_argument("--seed", type=int, default=2)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--service-time-s", type=float, default=0.05,
                    help="declared per-request service time the "
                    "autoscaler prices capacity with")
    ap.add_argument("--slo-tpot-ms", type=float, default=250.0,
                    help="declared TPOT p95 objective the autoscaled "
                    "rung must hold (generous for CPU; the gate is "
                    "'scaling to the floor must not degrade the "
                    "per-token service rate')")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line (bench.py --elastic)")
    args = ap.parse_args()

    cfg = TransformerConfig(
        vocab=VOCAB, dim=32, n_layers=2, n_heads=4, n_kv_heads=2
    )
    flat, _, _ = sequential_init(
        llama(cfg), jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((2, 8), jnp.int32),
    )
    reqs, stats = _make_trace(args)
    duration = reqs[-1].arrival_s - reqs[0].arrival_s

    static = _run_fleet(cfg, flat, reqs, autoscale=False,
                        slots=args.slots,
                        service_time_s=args.service_time_s)
    auto = _run_fleet(cfg, flat, reqs, autoscale=True,
                      slots=args.slots,
                      service_time_s=args.service_time_s)

    # HARD GATE 1: bitwise equality — scale events drop nothing.
    if auto["outs"] != static["outs"]:
        bad = next(
            i for i, (a, b) in enumerate(zip(auto["outs"],
                                             static["outs"]))
            if a != b
        )
        raise SystemExit(
            f"EXACTNESS FAIL: autoscaled rung diverged from static at "
            f"request {bad}: {auto['outs'][bad]} vs {static['outs'][bad]}"
        )

    # HARD GATE 2: the fleet breathed both ways and held the floor.
    downs = [a for a in auto["actions"] if a.startswith("down:")]
    ups = [a for a in auto["actions"] if a.startswith("up:")]
    if not downs or not ups:
        raise SystemExit(
            f"autoscaler did not breathe both ways on the bursty "
            f"trace: actions={auto['actions']}"
        )
    if min(auto["trajectory"]) < 1:
        raise SystemExit(
            f"trajectory dropped below the floor: {auto['trajectory']}"
        )

    # HARD GATE 3: less provisioned capacity than static peak.
    saved = static["replica_seconds"] - auto["replica_seconds"]
    if not saved > 0.0:
        raise SystemExit(
            f"autoscaling saved no capacity: "
            f"{auto['replica_seconds']:.3f} vs static "
            f"{static['replica_seconds']:.3f} replica-seconds"
        )
    saved_pct = 100.0 * saved / static["replica_seconds"]

    # HARD GATE 4: the declared per-token objective held while scaled.
    if auto["tpot_p95_ms"] > args.slo_tpot_ms:
        raise SystemExit(
            f"SLO FAIL: autoscaled TPOT p95 {auto['tpot_p95_ms']:.2f}ms "
            f"over the declared {args.slo_tpot_ms:.0f}ms objective"
        )

    out = {
        "bench": "elastic-autoscale",
        "platform": jax.devices()[0].platform,
        "requests": args.requests,
        "seed": args.seed,
        "slots_per_replica": args.slots,
        "replicas_peak": 2,
        "service_time_s": args.service_time_s,
        # honesty counters: the trace as generated, drops included
        "trace": {
            "generated": stats.generated,
            "skipped_too_long": stats.skipped_too_long,
            "burst_arrivals": stats.burst_arrivals,
            "duration_s": round(duration, 3),
        },
        "static": _pub(static),
        "autoscaled": {
            **_pub(auto),
            "actions": auto["actions"],
            "trajectory_min": min(auto["trajectory"]),
            "trajectory_max": max(auto["trajectory"]),
        },
        "capacity": {
            "static_replica_seconds": round(
                static["replica_seconds"], 3
            ),
            "autoscaled_replica_seconds": round(
                auto["replica_seconds"], 3
            ),
            "saved_pct": round(saved_pct, 1),
        },
        "slo": {
            "tpot_p95_objective_ms": args.slo_tpot_ms,
            "autoscaled_tpot_p95_ms": round(auto["tpot_p95_ms"], 3),
            "held": True,
        },
        "exactness_gated": True,
        "validated": True,
    }
    if args.json:
        print(json.dumps(out), flush=True)
        return
    print(
        f"elastic-autoscale: {stats.generated} requests "
        f"({stats.burst_arrivals} burst arrivals) over "
        f"{duration:.2f}s of trace time, 2 replicas x {args.slots} "
        f"slots\n"
        f"  static      {static['tokens_per_sec']:8.1f} tok/s  "
        f"ttft {static['ttft_p95_ms']:6.1f}ms p95  "
        f"tpot {static['tpot_p95_ms']:5.2f}ms p95  "
        f"{static['replica_seconds']:.2f} replica-s\n"
        f"  autoscaled  {auto['tokens_per_sec']:8.1f} tok/s  "
        f"ttft {auto['ttft_p95_ms']:6.1f}ms p95  "
        f"tpot {auto['tpot_p95_ms']:5.2f}ms p95  "
        f"{auto['replica_seconds']:.2f} replica-s "
        f"({len(downs)} down / {len(ups)} up, floor "
        f"{min(auto['trajectory'])})\n"
        f"  capacity saved {saved_pct:.1f}% vs static peak; outputs "
        f"bitwise-identical across scale events; TPOT p95 "
        f"{auto['tpot_p95_ms']:.2f}ms within the "
        f"{args.slo_tpot_ms:.0f}ms objective",
        flush=True,
    )


def _pub(r: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "tokens_per_sec": round(r["tokens_per_sec"], 1),
        "seconds": round(r["seconds"], 4),
        "tokens": r["tokens"],
        "ttft_p50_ms": round(r["ttft_p50_ms"], 2),
        "ttft_p95_ms": round(r["ttft_p95_ms"], 2),
        "tpot_p50_ms": round(r["tpot_p50_ms"], 3),
        "tpot_p95_ms": round(r["tpot_p95_ms"], 3),
        "replica_seconds": round(r["replica_seconds"], 3),
    }


if __name__ == "__main__":
    main()
