"""U-Net timeline / overlap-ablation benchmark.

Reference: benchmarks/unet-timeline/main.py:22-75 — ablates the engine's
concurrency features (dependencies, copy streams, portals) by
monkey-patching, sampling GPU utilization from a side process.  TPU-native
redesign: the engine's own :class:`~torchgpipe_tpu.utils.tracing.Timeline`
records per-cell intervals; the ``serialized`` experiment forces every cell
to completion before the next dispatch (no cross-stage overlap — the
ablation), and the busy/bubble fractions are compared against the
analytic GPipe bubble (n-1)/(m+n-1).
"""

from __future__ import annotations

import time

import click
import jax
import jax.numpy as jnp

from benchmarks.common import build_gpipe, mse
from torchgpipe_tpu.models import unet
from torchgpipe_tpu.utils.tracing import Timeline, simulate_pipeline


@click.command()
@click.option("--stages", default=4)
@click.option("--chunks", default=8)
@click.option("--image", default=64)
@click.option("--batch", default=16)
@click.option("--depth", default=3)
@click.option("--num-convs", default=2)
@click.option("--base-channels", default=16)
@click.option("--steps", default=5)
def main(stages, chunks, image, batch, depth, num_convs, base_channels, steps):
    layers = unet(
        depth=depth, num_convs=num_convs, base_channels=base_channels,
        output_channels=1,
    )
    x = jnp.zeros((batch, image, image, 3), jnp.float32)
    y = jnp.zeros((batch, image, image, 1), jnp.float32)
    in_spec = jax.ShapeDtypeStruct(x.shape, x.dtype)

    results = {}
    for mode in ("pipelined", "serialized"):
        tracer = Timeline(sync=(mode == "serialized"))
        model = build_gpipe(
            layers, None, stages, chunks, "except_last", tracer=tracer
        )
        params, state = model.init(jax.random.PRNGKey(0), in_spec)
        # Warm-up compile.
        loss, grads, state, _ = model.value_and_grad(
            params, state, x, y, mse, rng=jax.random.PRNGKey(1)
        )
        jax.block_until_ready(grads)
        tracer.reset()
        t0 = time.perf_counter()
        for s in range(steps):
            loss, grads, state, _ = model.value_and_grad(
                params, state, x, y, mse, rng=jax.random.PRNGKey(2 + s)
            )
        jax.block_until_ready(grads)
        dt = time.perf_counter() - t0
        results[mode] = batch * steps / dt
        print(f"--- {mode}: {results[mode]:.1f} samples/sec")
        print(tracer.summary())
        if mode == "serialized":
            # From true per-cell times, project the overlap-perfect makespan
            # and its bubble; gap vs the analytic (n-1)/(m+n-1) is stage
            # imbalance.
            sim = simulate_pipeline(tracer.events, stages)
            if sim is not None:
                makespan, busy, bubble = sim
                ideal_bubble = (stages - 1) / (chunks + stages - 1)
                print(
                    f"    projected pipelined makespan {makespan * 1e3:.1f}ms/"
                    f"step-pair, bubble {bubble:.2f} "
                    f"(analytic GPipe bubble {ideal_bubble:.2f})"
                )
    speedup = results["pipelined"] / results["serialized"]
    print(f"FINAL | unet-timeline: overlap speedup {speedup:.2f}x")


if __name__ == "__main__":
    main()
