"""U-Net (depth 5, 64 channels) speed benchmark.

Reference: benchmarks/unet-speed/main.py:22-78 — baseline + pipeline-1/2/4/8
on a (5, 64) U-Net with 192x192 inputs, MSE-style segmentation loss.
"""

from __future__ import annotations

import click
import jax.numpy as jnp

from benchmarks.common import bf16_option, build_gpipe, mse, run_speed
from torchgpipe_tpu.models import unet

EXPERIMENTS = {
    "baseline": (1, 40, 1),
    "pipeline-1": (1, 80, 2),
    "pipeline-2": (2, 160, 8),
    "pipeline-4": (4, 320, 16),
    "pipeline-8": (8, 640, 32),
}


@click.command()
@click.argument("experiment", type=click.Choice(sorted(EXPERIMENTS)))
@click.option("--epochs", default=3)
@click.option("--steps", default=10)
@click.option("--image", default=192)
@click.option("--batch", default=None, type=int)
@click.option("--depth", default=5)
@click.option("--num-convs", default=5)
@click.option("--base-channels", default=64)
@bf16_option
def main(experiment, epochs, steps, image, batch, depth, num_convs, base_channels, bf16):
    n, bsz, chunks = EXPERIMENTS[experiment]
    bsz = batch or bsz
    layers = unet(
        depth=depth, num_convs=num_convs, base_channels=base_channels,
        output_channels=1,
    )
    model = build_gpipe(layers, None, n, chunks, "except_last", bf16=bf16)
    x = jnp.zeros((bsz, image, image, 3), jnp.float32)
    y = jnp.zeros((bsz, image, image, 1), jnp.float32)
    tput = run_speed(
        model, x, y, mse, epochs=epochs, steps_per_epoch=steps, label=experiment
    )
    print(f"FINAL | unet-speed {experiment}: {tput:.1f} samples/sec")


if __name__ == "__main__":
    main()
