"""Decode throughput for the KV-cache generator (tokens/sec).

No reference counterpart (the reference is training-only) — this is the
measurement surface for :mod:`torchgpipe_tpu.models.generation`: one
compiled prefill+decode program, steady-state timed.  On TPU the decode
scan is HBM-bandwidth-bound (weights re-read per token); batch rows are
the lever, exactly like production decode servers.

Usage::

    env JAX_PLATFORMS=cpu python -m benchmarks.llama_decode --preset tiny
    python -m benchmarks.llama_decode --preset 1b --batch 8   # on TPU
"""

from __future__ import annotations

import argparse
import os
import time

import jax
import jax.numpy as jnp

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

from torchgpipe_tpu.layers import sequential_init
from torchgpipe_tpu.models.generation import generate
from torchgpipe_tpu.models.transformer import TransformerConfig, llama
from torchgpipe_tpu.utils.hw import chip_peak_bf16_flops

PRESETS = {
    # dim, n_layers, n_heads, n_kv_heads, vocab
    "tiny": (128, 4, 4, 2, 512),
    "small": (512, 8, 8, 4, 8192),
    "1b": (2048, 16, 32, 8, 128256),
}


def _host_fetch(out: object) -> None:
    """Materialize generated tokens on the host (end of the timed region).

    ``block_until_ready`` alone is NOT a completion barrier on the
    remote-tunnel backend (observed returning in sub-RTT time for a
    512-token decode — caught by the physical-floor gate); an actual
    device->host copy of the tokens cannot complete before the program
    ran.  The fetched array is tiny ([batch, new_tokens] int32), so the
    added transfer is one RTT, negligible against a multi-token decode."""
    import numpy as np

    tokens = out[0] if isinstance(out, tuple) else out
    np.asarray(jax.device_get(tokens))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", choices=sorted(PRESETS), default="tiny")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--bf16", action="store_true")
    ap.add_argument("--window", type=int, default=None,
                    help="sliding-window attention width")
    ap.add_argument("--ring", action="store_true",
                    help="ring KV caches (needs --window): O(window) "
                         "cache memory and per-step reads")
    ap.add_argument("--kv-quant", action="store_true",
                    help="int8 KV caches (half the bf16 footprint)")
    ap.add_argument("--draft", choices=sorted(PRESETS), default=None,
                    help="speculative decoding: preset of the DRAFT model "
                         "(untrained weights — greedy acceptance then "
                         "reflects draft/target agreement by luck only, so "
                         "the interesting column is ms/token at a GIVEN "
                         "acceptance; --self-draft shows the ceiling)")
    ap.add_argument("--self-draft", action="store_true",
                    help="speculative decoding with draft == target: 100%% "
                         "acceptance, the per-round overhead ceiling")
    ap.add_argument("--gamma", type=int, default=4,
                    help="drafts per speculative round")
    ap.add_argument("--w8", action="store_true",
                    help="weight-only int8 (models.quant): halve the "
                         "bf16 weight read traffic decode is bound by")
    args = ap.parse_args()

    dim, n_layers, nh, nkv, vocab = PRESETS[args.preset]
    cfg = TransformerConfig(
        vocab=vocab, dim=dim, n_layers=n_layers, n_heads=nh, n_kv_heads=nkv,
        dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
        attn_window=args.window,
    )
    b, s, new = args.batch, args.prompt_len, args.new_tokens
    spec = jax.ShapeDtypeStruct((b, s), jnp.int32)
    params, _, _ = sequential_init(llama(cfg), jax.random.PRNGKey(0), spec)
    if args.w8:
        from torchgpipe_tpu.models.quant import (
            quantize_params_int8, quantized_bytes,
        )

        params = quantize_params_int8(cfg, params)
        qb, fb = quantized_bytes(params, cfg.dtype)
        print(f"w8: projection weights {qb / 2**20:.1f} MiB int8 "
              f"(vs {fb / 2**20:.1f} MiB {jnp.dtype(cfg.dtype).name})",
              flush=True)
    prompt = jnp.mod(jnp.arange(b * s).reshape(b, s), vocab).astype(jnp.int32)

    mode = "ring" if args.ring else "full"
    spec_tag = ""
    acc_line = ""
    if args.self_draft or args.draft:
        if args.ring or args.kv_quant:
            raise SystemExit(
                "--draft/--self-draft use full fp caches: speculative "
                "rollback resets the cache frontier, which ring slot "
                "reuse cannot undo and int8 rows would re-quantize; "
                "drop --ring/--kv-quant"
            )
        from torchgpipe_tpu.models.generation import speculative_generate

        if args.self_draft:
            dcfg, dparams = cfg, params
            spec_tag = f", speculative self-draft g{args.gamma}"
        else:
            ddim, dnl, dnh, dnkv, dvocab = PRESETS[args.draft]
            dcfg = TransformerConfig(
                vocab=vocab, dim=ddim, n_layers=dnl, n_heads=dnh,
                n_kv_heads=dnkv, dtype=cfg.dtype, attn_window=args.window,
            )
            dparams, _, _ = sequential_init(
                llama(dcfg), jax.random.PRNGKey(1), spec
            )
            spec_tag = f", speculative draft={args.draft} g{args.gamma}"
        run = jax.jit(
            lambda p, dp, t: speculative_generate(
                cfg, p, dcfg, dp, t, new, gamma=args.gamma,
                return_stats=True,
            )
        )
        out, stats = run(params, dparams, prompt)
        jax.block_until_ready(out)  # compile
        best = float("inf")
        for i in range(args.steps):
            # A FRESH prompt buffer every timed call: the remote-tunnel
            # backend has been observed to satisfy a re-dispatch of
            # byte-identical inputs from a result cache (block_until_ready
            # returns instantly, "0.00 ms/token"), which no varying input
            # can fake.
            p_i = prompt.at[:, 0].set((i + 1) % vocab)
            t0 = time.perf_counter()
            out, stats = run(params, dparams, p_i)
            # Host-fetch the result INSIDE the timed region: on the
            # remote-tunnel backend block_until_ready has been observed
            # to return before execution (sub-RTT "timings" caught by
            # the floor gate below); materializing the tokens on the
            # host is the one thing a lazy backend cannot fake.
            _host_fetch(out)
            best = min(best, time.perf_counter() - t0)
        import numpy as np

        drafted = int(np.sum(np.asarray(stats.drafted)))
        accepted = int(np.sum(np.asarray(stats.accepted)))
        rounds = int(np.sum(np.asarray(stats.rounds)))
        acc_line = (
            f"  acceptance {accepted}/{drafted} "
            f"({100 * accepted / max(drafted, 1):.0f}%), "
            f"{rounds} target passes for {b * new} tokens "
            f"({b * new / max(rounds, 1):.2f} tokens/pass)"
        )
    else:
        run = jax.jit(
            lambda p, t: generate(
                cfg, p, t, max_new_tokens=new, cache_mode=mode,
                kv_quant=args.kv_quant,
            )
        )
        jax.block_until_ready(run(params, prompt))  # compile
        best = float("inf")
        for i in range(args.steps):
            # Fresh prompt buffer per call — see the speculative loop above.
            p_i = prompt.at[:, 0].set((i + 1) % vocab)
            t0 = time.perf_counter()
            _host_fetch(run(params, p_i))  # see the speculative loop
            best = min(best, time.perf_counter() - t0)
    toks = b * new
    wtag = (f", window {args.window} ({mode} cache)"
            if args.window else "")
    wtag += ", int8-kv" if args.kv_quant else ""
    wtag += ", int8-weights" if args.w8 else ""
    wtag += spec_tag
    # Measurement-integrity gate (the decode twin of bench.py's mfu>1
    # check): generating toks tokens costs at least ~2·n_params·toks
    # matmul FLOPs (weights applied once per token per row; speculative
    # runs cost MORE — draft + verify), so a run faster than that at the
    # chip's published bf16 peak can only mean the backend did not
    # execute the timed programs.  Refuse to publish it.
    peak = chip_peak_bf16_flops(jax.devices()[0])
    if peak is not None:
        n_params = sum(
            l.size for l in jax.tree_util.tree_leaves(params)
            if hasattr(l, "size")
        )
        # The input embedding's per-token cost is a gather (no matmul
        # FLOPs) — exclude its table so the floor stays a true lower
        # bound (also correct under tied heads, where excluding the
        # shared table merely lowers the floor further).
        n_params = max(n_params - cfg.vocab * cfg.dim, 0)
        floor_s = 2.0 * n_params * toks / peak
        if best < floor_s:
            raise SystemExit(
                f"IMPLAUSIBLE: measured {best * 1e3:.2f} ms for {toks} "
                f"tokens, below the {floor_s * 1e3:.2f} ms physical floor "
                f"(2·{n_params:.3g} params·{toks} tokens at chip peak "
                f"{peak:.3g} FLOP/s) — the backend did not execute the "
                "timed programs; not publishing"
            )
    print(
        f"{args.preset}{wtag}: batch {b}, prompt {s}, {new} new tokens -> "
        f"{toks / best:.1f} tokens/sec "
        f"({best * 1e3 / new:.2f} ms/token/batch, "
        f"platform {jax.devices()[0].platform})",
        flush=True,
    )
    if acc_line:
        print(acc_line, flush=True)


if __name__ == "__main__":
    main()
