"""Serving decode throughput: continuous vs static batching (tokens/sec).

The measurement surface for :mod:`torchgpipe_tpu.serving` — the number
BENCH_NOTES.md's "no decode number exists" gap asked for, measured the
way a decode server runs: a burst of ragged-length requests through the
slot-pooled engine, tokens/sec over the whole burst, continuous
(iteration-level) batching against the static run-to-longest baseline
(``wave_admission=True`` — same compiled programs, no recycling).

Measurement integrity (the BENCH_NOTES.md:472 contract):

* **Host-fetch inside the timed region, by construction** — the engine
  host-fetches every step's sampled tokens (streaming is the product
  feature), so ``block_until_ready`` laziness cannot fake a timing; the
  timed region ends only after the LAST generated token materialized on
  the host.
* **Physical-floor gate** — generating N tokens costs at least
  ``2·n_params·N`` matmul FLOPs; a run faster than that at the chip's
  published bf16 peak is refused, not published (the decode twin of
  bench.py's mfu>1 check).

Usage::

    env JAX_PLATFORMS=cpu python -m benchmarks.llama_serving --preset tiny
    python -m benchmarks.llama_serving --preset 1b --slots 8   # on TPU
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

from torchgpipe_tpu.layers import sequential_init
from torchgpipe_tpu.models.transformer import TransformerConfig, llama
from torchgpipe_tpu.serving import Engine
from torchgpipe_tpu.utils.hw import chip_peak_bf16_flops

from benchmarks.llama_decode import PRESETS


def _workload(args: argparse.Namespace, vocab: int):
    """Ragged, skewed request mix (seeded): short interactive requests
    threaded between long generations — the shape continuous batching
    exists for."""
    rng = np.random.RandomState(args.seed)
    reqs = []
    for i in range(args.requests):
        plen = int(rng.randint(2, args.prompt_len + 1))
        if i % 3 == 0:   # every third request is a long generation
            new = int(args.new_tokens)
        else:
            new = int(rng.randint(2, max(3, args.new_tokens // 4)))
        prompt = np.mod(
            rng.randint(0, vocab, (plen,)), vocab
        ).astype(np.int32)
        reqs.append((prompt, new))
    return reqs


def _run(mode: str, cfg, params, reqs, args) -> dict:
    from torchgpipe_tpu.serving import ServingMetrics

    eng = Engine(
        cfg, params,
        num_slots=args.slots,
        max_len=args.prompt_len + args.new_tokens,
        prefill_chunk=args.prefill_chunk,
        kv_quant=args.kv_quant,
        wave_admission=(mode == "static"),
    )
    # Warmup on the SAME engine (jax.jit caches per closure, so a fresh
    # engine would re-trace and re-compile inside the timed region);
    # reset the metrics so the snapshot covers only the timed burst.
    for p, n in reqs:
        eng.submit(p, n)
    eng.run()
    eng.metrics = ServingMetrics()
    t0 = time.perf_counter()
    rids = [eng.submit(p, n) for p, n in reqs]
    eng.run()
    # The engine host-fetched every token already; materialize the result
    # arrays anyway so the timed region provably ends on host data.
    toks = int(sum(eng.result(r).size for r in rids))
    dt = time.perf_counter() - t0
    assert eng.compile_stats == {"prefill": 1, "decode": 1}, (
        eng.compile_stats
    )
    snap = eng.metrics.snapshot()
    return {
        "mode": mode,
        "tokens": toks,
        "seconds": dt,
        "tokens_per_sec": toks / dt,
        "engine_steps": snap["engine_steps"],
        "tokens_per_step": snap["tokens_per_step"],
        "occupancy": snap["occupancy"],
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", choices=sorted(PRESETS), default="tiny")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--prefill-chunk", type=int, default=8)
    ap.add_argument("--bf16", action="store_true")
    ap.add_argument("--kv-quant", action="store_true",
                    help="int8 KV pool (half the bf16 footprint)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line (bench.py --decode-serving)")
    args = ap.parse_args()

    dim, n_layers, nh, nkv, vocab = PRESETS[args.preset]
    cfg = TransformerConfig(
        vocab=vocab, dim=dim, n_layers=n_layers, n_heads=nh,
        n_kv_heads=nkv,
        dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
    )
    spec = jax.ShapeDtypeStruct((1, args.prompt_len), jnp.int32)
    params, _, _ = sequential_init(llama(cfg), jax.random.PRNGKey(0), spec)
    reqs = _workload(args, vocab)

    results = {}
    for mode in ("continuous", "static"):
        # _run warms up (compiles both programs) and times a second
        # serving of the same burst on the same engine, steady-state.
        results[mode] = _run(mode, cfg, params, reqs, args)

    # Physical floor (decode twin of bench.py's mfu gate): refuse
    # sub-floor timings instead of publishing them.
    peak = chip_peak_bf16_flops(jax.devices()[0])
    gated = False
    if peak is not None:
        n_params = sum(
            l.size for l in jax.tree_util.tree_leaves(params)
            if hasattr(l, "size")
        )
        n_params = max(n_params - cfg.vocab * cfg.dim, 0)
        for r in results.values():
            floor_s = 2.0 * n_params * r["tokens"] / peak
            if r["seconds"] < floor_s:
                raise SystemExit(
                    f"IMPLAUSIBLE: {r['mode']} served {r['tokens']} tokens "
                    f"in {r['seconds'] * 1e3:.2f} ms, below the "
                    f"{floor_s * 1e3:.2f} ms physical floor — the backend "
                    "did not execute the timed programs; not publishing"
                )
        gated = True

    cont, stat = results["continuous"], results["static"]
    out = {
        "bench": "decode-serving",
        "preset": args.preset,
        "platform": jax.devices()[0].platform,
        "slots": args.slots,
        "requests": args.requests,
        "kv_quant": bool(args.kv_quant),
        "continuous_tokens_per_sec": round(cont["tokens_per_sec"], 2),
        "static_tokens_per_sec": round(stat["tokens_per_sec"], 2),
        "speedup": round(
            cont["tokens_per_sec"] / max(stat["tokens_per_sec"], 1e-9), 3
        ),
        "continuous_occupancy": round(cont["occupancy"], 3),
        "static_occupancy": round(stat["occupancy"], 3),
        # Steps/occupancy are the deterministic continuous-batching win
        # (scheduling, not machine noise): fewer compiled-step launches
        # for the same tokens.  tokens_per_sec on a contended host can
        # flip either way; on TPU, where decode steps are
        # HBM-bandwidth-bound at ~fixed cost, steps ~ time.
        "continuous_engine_steps": cont["engine_steps"],
        "static_engine_steps": stat["engine_steps"],
        "continuous_tokens_per_step": round(cont["tokens_per_step"], 3),
        "static_tokens_per_step": round(stat["tokens_per_step"], 3),
        "floor_gated": gated,
        "validated": gated,
    }
    if args.json:
        print(json.dumps(out), flush=True)
        return
    print(
        f"{args.preset}: {args.requests} ragged requests, {args.slots} "
        f"slots -> continuous {cont['tokens_per_sec']:.1f} tok/s "
        f"(occ {cont['occupancy']:.0%}, {cont['engine_steps']} steps) vs "
        f"static {stat['tokens_per_sec']:.1f} tok/s "
        f"(occ {stat['occupancy']:.0%}, {stat['engine_steps']} steps): "
        f"{out['speedup']:.2f}x, platform {out['platform']}",
        flush=True,
    )


if __name__ == "__main__":
    main()
