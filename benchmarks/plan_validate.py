"""Predicted-vs-measured rank-order validation of the static planner.

The planner (:mod:`torchgpipe_tpu.analysis.planner`) promises its
predicted-MFU RANKING is trustworthy without ever timing a device.  This
rung closes the loop on hardware anyone has: on the CPU tiny-llama
preset it builds the three checkpoint-mode candidates whose measured
step time differs by REAL work (recompute — ``never`` replays nothing,
``except_last`` replays ``m-1`` of ``m`` micro-batches, ``always`` all
of them; at ``chunks=2`` the expected time ratios are 1 : 1.17 : 1.33,
far above CPU timing noise), measures each with blocking steps, and
checks that the measured fastest-to-slowest order matches the planner's
predicted best-to-worst order.

Schedule-bubble predictions are deliberately NOT validated here: a
single CPU host serializes the per-cell schedule, so bubble structure
never reaches the wall clock — only total executed work does.  The
recompute axis is exactly that.

Emits one JSON line (the bench contract) and exits non-zero on a rank
mismatch::

    env JAX_PLATFORMS=cpu python bench.py --plan-validate
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Tuple

# The validated axis: checkpoint modes at chunks=2 (work ratios
# 1 : 7/6 : 4/3 — every adjacent gap is >= 14%).
MODES = ("never", "except_last", "always")
CHUNKS = 2


def _build(mode: str) -> Tuple[Any, Any, Any]:
    import jax
    import jax.numpy as jnp

    from benchmarks.llama_speed import PRESETS
    from torchgpipe_tpu.gpipe import GPipe
    from torchgpipe_tpu.models.transformer import TransformerConfig, llama

    dim, n_layers, n_heads, n_kv, vocab, mlp_ratio = PRESETS["tiny"]
    cfg = TransformerConfig(
        vocab=vocab, dim=dim, n_layers=n_layers, n_heads=n_heads,
        n_kv_heads=n_kv, mlp_ratio=mlp_ratio,
    )
    layers = llama(cfg)
    n_stages = 2
    base, rem = len(layers) // n_stages, len(layers) % n_stages
    balance = [
        base + (1 if j >= n_stages - rem else 0) for j in range(n_stages)
    ]
    model = GPipe(layers, balance=balance, chunks=CHUNKS, checkpoint=mode)
    x = jnp.zeros((8, 128), jnp.int32)
    return model, x, cfg


def _measure(model: Any, x: Any, steps: int = 5) -> float:
    """Median per-step seconds with per-step blocking (no async loop can
    over-report) after one compile warmup."""
    import jax

    from torchgpipe_tpu.models.transformer import cross_entropy

    def loss_fn(out: Any, tok: Any) -> Any:
        return cross_entropy(out[:, :-1, :], tok[:, 1:])

    in_spec = jax.ShapeDtypeStruct(x.shape, x.dtype)
    params, state = model.init(jax.random.PRNGKey(0), in_spec)
    rng = jax.random.PRNGKey(1)
    loss, grads, state, _ = model.value_and_grad(
        params, state, x, x, loss_fn, rng=rng
    )
    jax.block_until_ready((loss, grads))
    times: List[float] = []
    for i in range(steps):
        t0 = time.perf_counter()
        loss, grads, _, _ = model.value_and_grad(
            params, state, x, x, loss_fn, rng=jax.random.fold_in(rng, i)
        )
        jax.block_until_ready((loss, grads))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def run(steps: int = 5) -> Dict[str, Any]:
    """Plan, measure, compare.  Returns the result record (bench JSON)."""
    import jax

    from torchgpipe_tpu.analysis import planner

    model0, x, _ = _build(MODES[0])
    spec = jax.ShapeDtypeStruct(x.shape, x.dtype)
    report = planner.plan(
        model0, spec, hbm_budget_bytes=64 * 2 ** 30,
        chunks_options=(CHUNKS,),
        balance_options=[model0.balance],
    )
    scored = {
        p.checkpoint: p for p in report.candidates
        if p.schedule == "gpipe" and p.checkpoint in MODES
        and p.predicted_mfu is not None
    }
    missing = [m for m in MODES if m not in scored]
    if missing:
        raise RuntimeError(f"planner scored no candidate for {missing}")
    predicted = sorted(
        MODES, key=lambda m: -(scored[m].predicted_mfu or 0.0)
    )
    measured_times = {}
    for mode in MODES:
        model, x, _ = _build(mode)
        measured_times[mode] = _measure(model, x, steps=steps)
    measured = sorted(MODES, key=lambda m: measured_times[m])
    match = predicted == measured
    return {
        "metric": "plan-validate rank-order [tiny llama, cpu]",
        "value": 1.0 if match else 0.0,
        "unit": "match",
        "platform": "cpu",
        "validated": True,  # per-step blocking cannot over-report
        "match": match,
        "predicted_order": predicted,
        "measured_order": measured,
        "predicted_mfu": {
            m: round(scored[m].predicted_mfu or 0.0, 4) for m in MODES
        },
        "measured_step_s": {
            m: round(measured_times[m], 4) for m in MODES
        },
    }


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    result = run()
    print(json.dumps(result), flush=True)
    return 0 if result["match"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
