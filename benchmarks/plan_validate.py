"""Predicted-vs-measured rank-order validation of the static planner.

The planner (:mod:`torchgpipe_tpu.analysis.planner`) promises its
predicted-MFU RANKING is trustworthy without ever timing a device.  This
rung closes the loop on hardware anyone has: on the CPU tiny-llama
preset it builds the three checkpoint-mode candidates whose measured
step time differs by REAL work (recompute — ``never`` replays nothing,
``except_last`` replays ``m-1`` of ``m`` micro-batches, ``always`` all
of them; at ``chunks=2`` the expected time ratios are 1 : 1.17 : 1.33,
far above CPU timing noise), measures each with blocking steps, and
checks that the measured fastest-to-slowest order matches the planner's
predicted best-to-worst order.

Schedule-bubble predictions are deliberately NOT validated here: a
single CPU host serializes the per-cell schedule, so bubble structure
never reaches the wall clock — only total executed work does.  The
recompute axis is exactly that.

**Profile-guided extension** (the observe → replan loop's gate): one of
the rungs is ALSO traced with a ``sync=True`` timeline, reconciled, and
distilled into a measured :class:`~torchgpipe_tpu.obs.costmodel.
CostModel`; the planner then re-ranks the same candidates with
``cost_model=`` and BOTH rankings are scored against the measured step
times by pairwise rank agreement (Kendall concordance: the fraction of
candidate pairs ordered the same way).  The gate requires the
measured-cost ranking to agree at least as well as the analytic one —
feeding the planner real measurements must never make its ranking
worse.

**ZeRO rung** (the fully-sharded planner axis's gate): the tiny-llama
SPMD pipe on a pp=2 × dp=2 CPU mesh is stepped replicated and fully
sharded (``zero=3`` — params/grads/state stored at the fsdp layout,
gathered at use) from MATCHED params.  The gate is BITWISE-equal loss
at the matched params (the fsdp forward gathers exact copies, so the
first step's loss must be bit-identical; later steps drift at ULP
through psum-vs-reduce-scatter summation order and are only checked
finite).  The record reports the certifier's resident-bytes delta
(replicated param bytes vs the sharded residents, window beside it)
next to the measured wall ratio — BENCH_NOTES carries both.

Emits one JSON line (the bench contract) and exits non-zero on a rank
mismatch, an agreement regression, or a ZeRO gate failure::

    env JAX_PLATFORMS=cpu python bench.py --plan-validate
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Tuple

# The validated axis: checkpoint modes at chunks=2 (work ratios
# 1 : 7/6 : 4/3 — every adjacent gap is >= 14%).
MODES = ("never", "except_last", "always")
CHUNKS = 2


def _build(mode: str, tracer: Any = None) -> Tuple[Any, Any, Any]:
    import jax
    import jax.numpy as jnp

    from benchmarks.llama_speed import PRESETS
    from torchgpipe_tpu.gpipe import GPipe
    from torchgpipe_tpu.models.transformer import TransformerConfig, llama

    dim, n_layers, n_heads, n_kv, vocab, mlp_ratio = PRESETS["tiny"]
    cfg = TransformerConfig(
        vocab=vocab, dim=dim, n_layers=n_layers, n_heads=n_heads,
        n_kv_heads=n_kv, mlp_ratio=mlp_ratio,
    )
    layers = llama(cfg)
    n_stages = 2
    base, rem = len(layers) // n_stages, len(layers) % n_stages
    balance = [
        base + (1 if j >= n_stages - rem else 0) for j in range(n_stages)
    ]
    model = GPipe(layers, balance=balance, chunks=CHUNKS, checkpoint=mode,
                  tracer=tracer)
    x = jnp.zeros((8, 128), jnp.int32)
    return model, x, cfg


def _timed_step(model: Any, x: Any) -> Any:
    """Warm up (compile) and return ``run(i) -> seconds`` for one
    blocking training step of this model."""
    import jax

    from torchgpipe_tpu.models.transformer import cross_entropy

    def loss_fn(out: Any, tok: Any) -> Any:
        return cross_entropy(out[:, :-1, :], tok[:, 1:])

    in_spec = jax.ShapeDtypeStruct(x.shape, x.dtype)
    params, state = model.init(jax.random.PRNGKey(0), in_spec)
    rng = jax.random.PRNGKey(1)
    loss, grads, state, _ = model.value_and_grad(
        params, state, x, x, loss_fn, rng=rng
    )
    jax.block_until_ready((loss, grads))

    def run(i: int) -> float:
        t0 = time.perf_counter()
        loss, grads, _, _ = model.value_and_grad(
            params, state, x, x, loss_fn, rng=jax.random.fold_in(rng, i)
        )
        jax.block_until_ready((loss, grads))
        return time.perf_counter() - t0

    return run


def _measure(model: Any, x: Any, steps: int = 5) -> float:
    """Median per-step seconds with per-step blocking (no async loop can
    over-report) after one compile warmup."""
    run = _timed_step(model, x)
    times: List[float] = [run(i) for i in range(steps)]
    times.sort()
    return times[len(times) // 2]


def _measure_paired(steps: int = 7) -> Dict[str, float]:
    """Per-mode median step seconds over PAIRED rounds: all modes warm
    up first, then each round times one step of every mode
    back-to-back.  Host-load drift over the ~minute of measurement then
    shifts every mode's round together instead of penalizing whichever
    mode ran during the slow window — the flightrec-overhead rung's
    paired-rounds treatment (its unpaired medians drifted ±4-5% on the
    CI host, which is MORE than the ~17% never→except_last work gap
    divided across a ~40% fixed-overhead floor)."""
    runners = {}
    for mode in MODES:
        model, x, _ = _build(mode)
        runners[mode] = _timed_step(model, x)
    times: Dict[str, List[float]] = {m: [] for m in MODES}
    for i in range(steps):
        for mode in MODES:
            times[mode].append(runners[mode](i))
    out = {}
    for mode, ts in times.items():
        ts.sort()
        out[mode] = ts[len(ts) // 2]
    return out


def _rank_agreement(
    order: List[str], measured_times: Dict[str, float]
) -> float:
    """Pairwise (Kendall) concordance of a predicted best-to-worst
    ``order`` against measured step times: the fraction of candidate
    pairs the prediction orders the same way the clock does (1.0 =
    identical ranking)."""
    import itertools

    pairs = list(itertools.combinations(order, 2))
    ok = sum(
        1 for a, b in pairs if measured_times[a] <= measured_times[b]
    )
    return ok / len(pairs)


def _distill_cost_model(steps: int) -> Any:
    """Trace the MODES[0] rung with a sync=True timeline and distill
    the measured reconciliation into a CostModel (warm-up excluded —
    compile time must not contaminate the medians)."""
    from torchgpipe_tpu import obs
    from torchgpipe_tpu.analysis.events import events_for
    from torchgpipe_tpu.utils.tracing import Timeline

    tracer = Timeline(sync=True)
    model, x, _ = _build(MODES[0], tracer=tracer)
    run = _timed_step(model, x)  # warm-up compile happens here
    tracer.reset()  # drop the compile-contaminated warm-up spans
    for i in range(steps):
        run(i)
    report = obs.reconcile(tracer, events_for(model))
    return report.cost_model(model)


def _zero3_rung(steps: int = 5) -> Dict[str, Any]:
    """Replicated vs fully-sharded (``zero=3``) measured step time at
    MATCHED params on the pp=2 × dp=2 CPU mesh (module docstring, ZeRO
    rung).  Returns the rung's record; ``{"skipped": ...}`` when the
    host exposes fewer than 4 devices."""
    import dataclasses as dc

    import jax
    import numpy as np
    import optax

    from benchmarks.llama_speed import PRESETS
    from torchgpipe_tpu.analysis import sharding as shd
    from torchgpipe_tpu.models.transformer import (
        TransformerConfig, cross_entropy, llama_spmd,
    )
    from torchgpipe_tpu.spmd import SpmdGPipe, make_mesh

    if len(jax.devices()) < 4:
        return {"skipped": "needs >= 4 host devices (pp=2 x dp=2)"}
    dim, n_layers, n_heads, n_kv, vocab, mlp_ratio = PRESETS["tiny"]
    cfg = TransformerConfig(
        vocab=vocab, dim=dim, n_layers=n_layers, n_heads=n_heads,
        n_kv_heads=n_kv, mlp_ratio=mlp_ratio,
    )
    block, pre, post = llama_spmd(cfg, 2)
    mesh = make_mesh(2, 2)

    def loss_fn(out: Any, tok: Any) -> Any:
        return cross_entropy(out[:, :-1, :], tok[:, 1:])

    rep = SpmdGPipe(block, 2, mesh, chunks=CHUNKS, loss_fn=loss_fn,
                    pre=pre, post=post, dp_axis="dp")
    shp = dc.replace(rep, fsdp=True, zero_update=3)
    x = jax.random.randint(jax.random.PRNGKey(1), (8, 128), 0, vocab)
    spec = jax.ShapeDtypeStruct(x.shape, x.dtype)
    host = rep.init(jax.random.PRNGKey(0), spec)
    opt = optax.adamw(1e-3)
    tmap = jax.tree_util.tree_map

    runners: Dict[str, Any] = {}
    first_losses: Dict[str, Any] = {}
    for name, pipe, zero in (("replicated", rep, 0), ("zero3", shp, 3)):
        params = pipe.place(tmap(np.asarray, host))
        step = pipe.make_train_step(opt, donate=False, zero=zero)
        state = pipe.zero_opt_state(opt, params, zero=zero)
        # Compile + the matched-params step whose loss the gate pins.
        loss, params, state = step(params, state, x, x)
        first_losses[name] = np.asarray(jax.block_until_ready(loss))

        def run_one(
            i: int, _step: Any = step, _box: List[Any] = [params, state]
        ) -> Tuple[float, float]:
            t0 = time.perf_counter()
            loss, _box[0], _box[1] = _step(_box[0], _box[1], x, x)
            jax.block_until_ready(loss)
            return time.perf_counter() - t0, float(loss)

        runners[name] = run_one
    bitwise = bool(np.array_equal(
        first_losses["replicated"], first_losses["zero3"]
    ))
    # Paired rounds (the _measure_paired treatment): host-load drift
    # shifts both variants' round together.
    times: Dict[str, List[float]] = {n: [] for n in runners}
    finite = True
    for i in range(steps):
        for name, run_one in runners.items():
            dt, lv = run_one(i)
            times[name].append(dt)
            finite = finite and bool(np.isfinite(lv))
    med: Dict[str, float] = {}
    for name, ts in times.items():
        ts.sort()
        med[name] = ts[len(ts) // 2]
    # The certifier's resident-bytes story, reported beside the wall
    # ratio: replicated residents vs sharded residents (+ the transient
    # gathered window the memory certification charges).
    lay_r = shd.verify_layout(rep, spec)
    lay_s = shd.verify_layout(shp, spec)
    return {
        "bitwise_matched_loss": bitwise,
        "finite": finite,
        "step_s": {n: round(t, 4) for n, t in med.items()},
        "wall_ratio_zero3_over_replicated": round(
            med["zero3"] / med["replicated"], 3
        ),
        "resident_param_bytes": {
            "replicated": int(lay_r.param_bytes_local),
            "zero3_sharded": int(lay_s.param_bytes_local),
            "zero3_gathered_window": int(lay_s.gathered_window_bytes),
        },
        "resident_bytes_delta": int(
            lay_r.param_bytes_local - lay_s.param_bytes_local
        ),
    }


def run(steps: int = 5) -> Dict[str, Any]:
    """Plan, measure, compare.  Returns the result record (bench JSON)."""
    import jax

    from torchgpipe_tpu.analysis import planner

    model0, x, _ = _build(MODES[0])
    spec = jax.ShapeDtypeStruct(x.shape, x.dtype)
    options = {
        "chunks_options": (CHUNKS,),
        "balance_options": [model0.balance],
    }
    report = planner.plan(
        model0, spec, hbm_budget_bytes=64 * 2 ** 30, **options
    )

    def scored_of(rep: Any) -> Dict[str, Any]:
        out = {
            p.checkpoint: p for p in rep.candidates
            if p.schedule == "gpipe" and p.checkpoint in MODES
            and p.predicted_mfu is not None
        }
        missing = [m for m in MODES if m not in out]
        if missing:
            raise RuntimeError(
                f"planner scored no candidate for {missing}"
            )
        return out

    scored = scored_of(report)
    predicted = sorted(
        MODES, key=lambda m: -(scored[m].predicted_mfu or 0.0)
    )
    measured_times = _measure_paired(steps=max(steps, 7))
    measured = sorted(MODES, key=lambda m: measured_times[m])
    match = predicted == measured

    # Profile-guided half: re-rank the same candidates with a cost
    # model distilled from a traced run of the MODES[0] rung; the
    # measured ranking's pairwise agreement with the clock must not be
    # worse than the analytic ranking's (module docstring).
    cm = _distill_cost_model(steps=3)
    report_m = planner.plan(
        model0, spec, hbm_budget_bytes=64 * 2 ** 30, cost_model=cm,
        **options,
    )
    scored_m = scored_of(report_m)
    predicted_m = sorted(
        MODES, key=lambda m: -(scored_m[m].predicted_mfu or 0.0)
    )
    agree_analytic = _rank_agreement(predicted, measured_times)
    agree_measured = _rank_agreement(predicted_m, measured_times)
    no_regression = agree_measured >= agree_analytic
    priced_by = {m: scored_m[m].priced_by for m in MODES}
    zero3 = _zero3_rung(steps=steps)
    zero3_ok = (
        "skipped" in zero3
        or (zero3["bitwise_matched_loss"] and zero3["finite"])
    )
    ok = match and no_regression and zero3_ok
    return {
        "metric": "plan-validate rank-order [tiny llama, cpu]",
        "value": 1.0 if ok else 0.0,
        "unit": "match",
        "platform": "cpu",
        "validated": True,  # per-step blocking cannot over-report
        "match": match,
        "predicted_order": predicted,
        "measured_order": measured,
        "predicted_mfu": {
            m: round(scored[m].predicted_mfu or 0.0, 4) for m in MODES
        },
        "measured_step_s": {
            m: round(measured_times[m], 4) for m in MODES
        },
        "measured_cost_order": predicted_m,
        "measured_cost_mfu": {
            m: round(scored_m[m].predicted_mfu or 0.0, 4) for m in MODES
        },
        "priced_by": priced_by,
        "rank_agreement_analytic": round(agree_analytic, 4),
        "rank_agreement_measured": round(agree_measured, 4),
        "measured_not_worse": no_regression,
        "zero3": zero3,
    }


def main() -> int:
    import os
    import sys

    # The ZeRO rung needs a pp=2 x dp=2 host mesh; the flag only works
    # BEFORE the first jax import in this process (the rung degrades to
    # a skip note otherwise).
    if "jax" not in sys.modules:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    result = run()
    print(json.dumps(result), flush=True)
    return 0 if result["value"] == 1.0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
