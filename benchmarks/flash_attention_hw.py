"""Hardware validation for the Pallas flash-attention kernels.

Runs BOTH kernel families (resident and streaming) on the live backend —
no ``interpret=True`` — checking numerics against the dense XLA oracle and
timing fwd+bwd.  This is the on-device complement to
``tests/test_flash_attention.py`` (which runs everything in interpret mode
on CPU): a Mosaic lowering difference that interpret mode cannot catch
shows up here as a numerics failure.

Usage::

    python benchmarks/flash_attention_hw.py [--seqs 2048,4096] [--iters 20]

Prints one table row per (seq, variant) with max|err| vs dense for output
and gradients, plus fwd+bwd wall time; exits non-zero on a tolerance
failure so it can gate a hardware CI lane.

Reference anchor: the reference has no fused-attention kernels (it is
CNN-oriented, CUDA streams only) — this is new TPU-native capability; the
oracle-comparison pattern mirrors its transparency tests
(reference: tests/test_transparency.py:7-42).
"""

from __future__ import annotations

import argparse
import sys
import time

import jax
import jax.numpy as jnp

from torchgpipe_tpu.ops.flash_attention import flash_attention
from torchgpipe_tpu.parallel.ring_attention import full_attention

# The dense oracle is the SAME full_attention the interpret-mode kernel
# tests compare against (tests/test_flash_attention.py), so the hardware
# numbers here and the CI oracle can never drift apart.
dense_attention = full_attention


def _is_oom(e: Exception) -> bool:
    msg = str(e)
    return ("RESOURCE_EXHAUSTED" in msg or "Ran out of memory" in msg
            or "Exceeded hbm capacity" in msg)


def run_case(seq, streaming, b=4, h=16, g=8, d=128, dtype=jnp.bfloat16,
             iters=20):
    """Returns (out_err, grad_err, t_flash_ms, t_dense_ms).

    The dense oracle's score matrix is O(b·h·seq²) — at the long
    sequence lengths the STREAMING kernel exists for (seq > 8k, where
    resident K/V tips past ``_STREAM_BYTES`` of VMEM) it cannot fit HBM.
    A dense-side failure therefore reports ``(nan, nan, t_flash, nan)``
    rather than failing the case: the flash row still proves the kernel
    runs (and how fast) in the regime the oracle cannot enter; numeric
    equivalence in that regime is covered by the interpret-mode CI tests
    (tests/test_flash_attention.py) and by the 2k/4k oracle rows here."""
    ks = jax.random.split(jax.random.PRNGKey(seq), 4)
    q = jax.random.normal(ks[0], (b, seq, h, d), dtype)
    k = jax.random.normal(ks[1], (b, seq, g, d), dtype)
    v = jax.random.normal(ks[2], (b, seq, g, d), dtype)
    do = jax.random.normal(ks[3], (b, seq, h, d), dtype)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, streaming=streaming)
            .astype(jnp.float32) * do.astype(jnp.float32))

    def loss_dense(q, k, v):
        return jnp.sum(
            dense_attention(q, k, v).astype(jnp.float32)
            * do.astype(jnp.float32))

    def maxerr(a, bb):
        return float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                     - bb.astype(jnp.float32))))

    flash_g = jax.jit(jax.value_and_grad(loss_flash, argnums=(0, 1, 2)))
    out_f = jax.jit(
        lambda q, k, v: flash_attention(q, k, v, causal=True,
                                        streaming=streaming))(q, k, v)
    _, grads_f = flash_g(q, k, v)
    jax.block_until_ready((out_f, grads_f))

    t0 = time.perf_counter()
    for _ in range(iters):
        val, grads = flash_g(q, k, v)
    jax.block_until_ready((val, grads))
    t_flash = (time.perf_counter() - t0) / iters * 1e3

    try:
        dense_g = jax.jit(jax.value_and_grad(loss_dense, argnums=(0, 1, 2)))
        out_d = jax.jit(lambda q, k, v: dense_attention(q, k, v))(q, k, v)
        _, grads_d = dense_g(q, k, v)
        jax.block_until_ready((out_d, grads_d))
    except Exception as e:  # noqa: BLE001 — only OOM may stand down
        # Only a resource failure excuses the oracle — any other error
        # (lowering regression, shape bug) must still fail the case, or
        # this script's numerics gate silently stops gating.
        if not _is_oom(e):
            raise
        return float("nan"), float("nan"), t_flash, float("nan")

    out_err = maxerr(out_f, out_d)
    grad_err = max(maxerr(gf, gd) for gf, gd in zip(grads_f, grads_d))

    try:
        t0 = time.perf_counter()
        for _ in range(iters):
            val, grads = dense_g(q, k, v)
        jax.block_until_ready((val, grads))
        t_dense = (time.perf_counter() - t0) / iters * 1e3
    except Exception as e:  # noqa: BLE001 — same OOM excuse as above
        if not _is_oom(e):
            raise
        t_dense = float("nan")  # numerics landed; only the timing OOM'd

    return out_err, grad_err, t_flash, t_dense


def run_decode_case(S, pos0, window, b=8, h=16, g=8, d=128,
                    dtype=jnp.bfloat16, iters=50, chain=256,
                    interpret=False):
    """Decode-kernel row: numerics vs the dense cache read + per-step
    latency at live length ``pos0`` (flash cost should FOLLOW pos0 —
    its K-block loop is length-bounded — while dense streams all S rows
    regardless).

    Timing fetches the result to the HOST each measurement: on the
    remote-tunnel backend ``block_until_ready`` alone has been observed
    to return before execution (see benchmarks/llama_decode.py); a
    device->host copy cannot complete early.  A single decode step is
    far cheaper than one tunnel round trip (~tens of ms), so each
    measured program CHAINS ``chain`` data-dependent steps in one
    ``lax.scan`` — per-step cost is the host-fetched total over
    ``chain``, amortizing the RTT floor to total/chain instead of
    swamping the kernel entirely (observed: un-chained rows read ~68 ms
    for BOTH variants at every length — pure RTT)."""
    import numpy as np

    from torchgpipe_tpu.models.generation import _attend_chunk
    from torchgpipe_tpu.ops.flash_attention import flash_decode_attention

    ks = jax.random.split(jax.random.PRNGKey(S + pos0), 3)
    q = jax.random.normal(ks[0], (b, 1, h, d), dtype)
    ck = jax.random.normal(ks[1], (b, S, g, d), dtype)
    cv = jax.random.normal(ks[2], (b, S, g, d), dtype)

    flash = jax.jit(lambda qq, p: flash_decode_attention(
        qq, ck, cv, p, window=window, interpret=interpret))
    dense = jax.jit(lambda qq, p: _attend_chunk(
        qq, ck, cv, p, window, use_flash=False))

    p0 = jnp.int32(pos0)
    out_f = flash(q, p0)
    out_d = dense(q, p0)
    err = float(jnp.max(jnp.abs(out_f - out_d)))

    def chained(attend):
        # The next step's queries depend on this step's output, so no
        # backend can overlap or elide steps; same shapes throughout.
        def body(c, _):
            o = attend(c, p0)
            c2 = (c + 1e-6 * o.reshape(c.shape)).astype(c.dtype)
            return c2, ()

        def many(qq):
            c, _ = jax.lax.scan(body, qq, None, length=chain)
            return c

        return jax.jit(many)

    def clock(fn):
        best = float("inf")
        for i in range(iters):
            q_i = q * (1.0 + 1e-3 * i)
            t0 = time.perf_counter()
            np.asarray(jax.device_get(fn(q_i)))
            best = min(best, time.perf_counter() - t0)
        return best * 1e3 / chain

    flash_n, dense_n = chained(
        lambda qq, p: flash_decode_attention(
            qq, ck, cv, p, window=window, interpret=interpret)
    ), chained(
        lambda qq, p: _attend_chunk(qq, ck, cv, p, window, use_flash=False)
    )
    np.asarray(jax.device_get(flash_n(q)))  # compile
    np.asarray(jax.device_get(dense_n(q)))
    return err, clock(flash_n), clock(dense_n)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default="2048,4096")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--decode", action="store_true",
                    help="run the DECODE kernel rows instead (single-query "
                         "cache attention: numerics + per-step latency at "
                         "1/4, 1/2 and full live length)")
    ap.add_argument("--chain", type=int, default=None,
                    help="decode steps chained per timed program: the "
                         "remote tunnel's ~70 ms host-fetch RTT adds "
                         "RTT/chain to every per-step number, so the chain "
                         "must be deep enough that the kernel's own "
                         "sub-ms cost shows through (default 256 on TPU -> "
                         "~0.27 ms of RTT per step; default 1 off-TPU, "
                         "where the kernel runs in interpret mode and a "
                         "256-step scan of it would take minutes)")
    ap.add_argument("--batch", type=int, default=4,
                    help="batch size (drop to 1 for long-seq cases so the "
                         "dense oracle's O(seq^2) scores have a chance)")
    # bf16 inputs with f32 accumulation: output tolerance scales with the
    # bf16 ulp at the magnitudes involved; gradients accumulate over seq.
    ap.add_argument("--tol-out", type=float, default=0.08)
    ap.add_argument("--tol-grad", type=float, default=0.5)
    args = ap.parse_args()

    dev = jax.devices()[0]
    print(f"backend: {dev.platform} ({getattr(dev, 'device_kind', '?')})")
    if args.chain is None:
        # Off-TPU the kernel runs in interpret mode: chaining 256
        # interpreted steps per timed program would take minutes, and the
        # tunnel-RTT rationale for chaining doesn't apply there.
        args.chain = 256 if dev.platform == "tpu" else 1
    failed = False
    if args.decode:
        print(f"{'S':>6} {'pos0':>6} {'window':>7} {'out err':>9} "
              f"{'flash ms':>9} {'dense ms':>9}")
        for seq in [int(s) for s in args.seqs.split(",")]:
            for pos0 in (seq // 4, seq // 2, seq - 1):
                for window in (None, 1024):
                    try:
                        err, tf, td = run_decode_case(
                            seq, pos0, window, b=args.batch,
                            iters=args.iters, chain=args.chain,
                            interpret=dev.platform != "tpu")
                    except Exception as e:  # noqa: BLE001 — report, continue
                        print(f"{seq:>6} {pos0:>6} {str(window):>7} "
                              f"FAILED: {type(e).__name__}: {str(e)[:100]}")
                        failed = True
                        continue
                    ok = err <= args.tol_out
                    failed |= not ok
                    print(f"{seq:>6} {pos0:>6} {str(window):>7} "
                          f"{err:>9.4f} {tf:>9.3f} {td:>9.3f}  "
                          f"{'ok' if ok else 'TOLERANCE-FAIL'}")
        sys.exit(1 if failed else 0)
    print(f"{'seq':>6} {'variant':>9} {'out err':>9} {'grad err':>9} "
          f"{'flash ms':>9} {'dense ms':>9}")
    for seq in [int(s) for s in args.seqs.split(",")]:
        for streaming in (False, True):
            name = "streaming" if streaming else "resident"
            try:
                oe, ge, tf, td = run_case(seq, streaming, b=args.batch,
                                          iters=args.iters)
            except Exception as e:  # noqa: BLE001 — report and continue
                print(f"{seq:>6} {name:>9} FAILED: {type(e).__name__}: "
                      f"{str(e)[:120]}")
                failed = True
                continue
            if td != td:  # dense oracle OOM'd: flash-only row, not a failure
                print(f"{seq:>6} {name:>9} {'n/a':>9} {'n/a':>9} "
                      f"{tf:>9.2f} {'OOM':>9}  ok (oracle infeasible)")
                continue
            ok = oe <= args.tol_out and ge <= args.tol_grad
            failed |= not ok
            print(f"{seq:>6} {name:>9} {oe:>9.4f} {ge:>9.4f} "
                  f"{tf:>9.2f} {td:>9.2f}  {'ok' if ok else 'TOLERANCE-FAIL'}")
    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
