"""Benchmark drivers reproducing the reference's experiment grids on TPU.

Counterpart of the reference's ``benchmarks/`` tree (SURVEY.md §2.4): speed
(samples/sec) and memory (params + per-device peak bytes) drivers for
AmoebaNet-D / sequential ResNet-101 / U-Net, an accuracy driver, and the
multi-process distributed driver.  Run any driver with ``--help``::

    python -m benchmarks.amoebanetd_speed n8m32
    python -m benchmarks.unet_memory pipeline-4
    python -m benchmarks.distributed_accuracy --rank 0 --world 2 ...
"""
