"""Megastep ladder: K optimizer steps per compiled program, measured.

The dispatch-amortization rung ``bench.py --megastep`` runs: the SPMD
tiny-llama preset trained through ``make_train_step(megastep=K)`` for
K over the canonical ladder (``tune.megastep_options`` — the same axis
the planner sweeps), batches streamed through the sharding-aware
double-buffered prefetcher.  Reported per K: mean milliseconds per
OPTIMIZER step (wall clock over the timed window divided by
``megasteps x K``) — so the ladder isolates exactly what megastep
amortizes: per-step Python dispatch, host sync, and guard bookkeeping.

Measurement integrity: every timed window ends on
``block_until_ready`` of the final params leaf (no async laziness), a
warmup megastep per K keeps compiles out of the timed region, and the
SAME stacked batch values feed every K (losses must agree across the
ladder — asserted, since megastep(K) is bitwise K single steps).

Usage::

    env JAX_PLATFORMS=cpu python bench.py --megastep            # CPU ref
    env JAX_PLATFORMS=cpu python -m benchmarks.llama_megastep --steps 32
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    import optax

    from torchgpipe_tpu import tune
    from torchgpipe_tpu.models.transformer import (
        TransformerConfig,
        cross_entropy,
        llama_spmd,
    )
    from torchgpipe_tpu.spmd import SpmdGPipe, make_mesh
    from torchgpipe_tpu.utils.data import prefetch_to_pipe

    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=2)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--chunks", type=int, default=2)
    ap.add_argument("--steps", type=int, default=32,
                    help="timed OPTIMIZER steps per K (divisible by "
                         "every K in the ladder)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line (bench.py --megastep)")
    args = ap.parse_args(argv)

    # The canonical ladder, filtered to Ks dividing the timed window —
    # the same divisibility contract the planner's hook-cadence filter
    # enforces.
    ladder = tune.megastep_options(steps=args.steps)
    # CPU tiny preset (llama_speed PRESETS["tiny"]), scaled to the pp
    # mesh actually present.
    n = min(args.stages, len(jax.devices()))
    cfg = TransformerConfig(
        vocab=1024, dim=256, n_layers=2 * n, n_heads=8, n_kv_heads=4,
        mlp_ratio=4.0,
    )
    block, pre, post = llama_spmd(cfg, n)
    mesh = make_mesh(n, devices=jax.devices()[:n])
    pipe = SpmdGPipe(
        block, n, mesh, chunks=args.chunks, loss_fn=cross_entropy,
        pre=pre, post=post, checkpoint="except_last",
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (args.batch, args.seq + 1), 0, cfg.vocab
    )
    inputs, labels = tokens[:, :-1], tokens[:, 1:]
    in_spec = jax.ShapeDtypeStruct(inputs.shape, inputs.dtype)
    opt = optax.adamw(3e-4)
    params0 = pipe.init(jax.random.PRNGKey(0), in_spec)

    results = []
    final_loss = {}
    for K in ladder:
        step = pipe.make_train_step(opt, donate=True, megastep=K)
        # [K, B, S]-stacked batches through the sharding-aware
        # prefetcher (leading K axis unsharded).
        stacked = (
            jnp.broadcast_to(inputs, (K,) + inputs.shape),
            jnp.broadcast_to(labels, (K,) + labels.shape),
        ) if K > 1 else (inputs, labels)
        batches = prefetch_to_pipe(
            iter(lambda: stacked, None), pipe, size=2, stacked=K > 1
        )
        megasteps = args.steps // K
        # Warmup (compile) on a THROWAWAY state so every K's timed
        # window starts from params0 and runs exactly --steps optimizer
        # steps — the cross-K loss-agreement gate below depends on it.
        wp = jax.tree_util.tree_map(jnp.copy, params0)
        wo = pipe.place_tree(opt.init(wp))
        x, y = next(batches)
        jax.block_until_ready(step(wp, wo, x, y)[1])
        params = jax.tree_util.tree_map(jnp.copy, params0)
        opt_state = pipe.place_tree(opt.init(params))
        t0 = time.perf_counter()
        for _ in range(megasteps):
            x, y = next(batches)
            out = step(params, opt_state, x, y)
            loss, params, opt_state = out[0], out[1], out[2]
        jax.block_until_ready(params)
        dt = time.perf_counter() - t0
        ms_per_step = dt * 1e3 / (megasteps * K)
        final_loss[K] = float(np.asarray(loss).reshape(-1)[-1])
        results.append({
            "megastep": K,
            "optimizer_steps": megasteps * K,
            "program_dispatches": megasteps,
            "ms_per_optimizer_step": ms_per_step,
        })
        print(
            f"megastep K={K:<3d}: {ms_per_step:8.2f} ms/step "
            f"({megasteps} dispatches for {megasteps * K} steps, "
            f"last loss {final_loss[K]:.4f})",
            flush=True,
        )
    # Same data + warmup step per K and megastep(K) == K single steps:
    # every ladder entry must land on the same trained loss.
    losses = {round(v, 3) for v in final_loss.values()}
    assert len(losses) == 1, (
        f"megastep ladder diverged across K: {final_loss} — the "
        "bitwise K-step contract is broken; not publishing"
    )
    base = results[0]["ms_per_optimizer_step"]
    for r in results:
        r["speedup_vs_k1"] = base / r["ms_per_optimizer_step"]
    line = {
        "bench": "megastep",
        "platform": jax.devices()[0].platform,
        "stages": n,
        "batch": args.batch,
        "seq": args.seq,
        "results": results,
    }
    if args.json:
        print("BENCH_JSON " + json.dumps(line), flush=True)
    best = max(results, key=lambda r: r["speedup_vs_k1"])
    print(
        f"FINAL | megastep ladder [{line['platform']}]: K={best['megastep']} "
        f"is {best['speedup_vs_k1']:.2f}x K=1 "
        f"({best['ms_per_optimizer_step']:.2f} vs {base:.2f} ms/step)",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
