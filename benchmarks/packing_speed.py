"""Sequence packing: stop paying for padding, measured.

The ragged-corpus rung ``bench.py --packing`` runs TWO comparisons over
ONE corpus of variable-length documents (~50% natural padding):

* **Training** — the SAME documents through the SAME SpmdGPipe tiny
  llama, once PADDED one-per-row (the classic layout) and once PACKED
  by ``utils.data.pack_documents`` (segment-aware attention, packed
  positions).  Packing shrinks the number of fixed ``[B, S]`` blocks by
  ~the padding fraction, so wall-clock REAL tokens/s must move toward
  the ``1 / (1 - pad_fraction)`` bound — the gate is packed tokens/s >=
  1.3x padded at ~50% padding.  Equivalence is asserted, not assumed:
  per-document losses from the packed run must match each document's
  padded-row loss within a pinned tolerance (reduction order differs
  between the two layouts; everything else is the same math — the
  bitwise version of this gate lives in tests/test_packing.py).
* **Serving** — a ragged BURSTY request mix through the serving engine
  with the prefill bucket ladder ON (``prefill_chunk=(1, 2, 4, 8)``)
  vs OFF (single max chunk), reporting TTFT/TPOT percentiles for both.
  Same documents as prompts, same compiled-program discipline — the
  ladder serves short prompts from small programs instead of the max
  chunk's FLOPs.

Usage::

    env JAX_PLATFORMS=cpu python bench.py --packing             # CPU ref
    env JAX_PLATFORMS=cpu python -m benchmarks.packing_speed --json
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

# The pinned packed-vs-padded per-document loss tolerance: the two
# layouts run identical per-position math, but einsum reduction order
# differs between a [B, S] padded row and the packed block it lands in
# (f32 accumulation; documented in docs/tuning.md).
LOSS_TOL = 5e-4


def _corpus(rng: np.random.RandomState, n_docs: int, seq: int, vocab: int):
    """Ragged documents, uniform lengths in [seq//16, seq] — ~50%
    natural padding against one-per-row [seq] blocks."""
    lo = max(2, seq // 16)
    return [
        rng.randint(1, vocab, size=int(rng.randint(lo, seq + 1)))
        .astype(np.int32)
        for _ in range(n_docs)
    ]


def _train_side(args, out):
    import optax

    from torchgpipe_tpu.models.transformer import (
        TransformerConfig,
        llama_spmd,
        packed_cross_entropy_sum,
        per_document_losses,
    )
    from torchgpipe_tpu.spmd import SpmdGPipe, make_mesh
    from torchgpipe_tpu.utils import data as D

    rng = np.random.RandomState(0)
    docs = _corpus(rng, args.docs, args.seq, args.vocab)
    n_real = sum(len(d) for d in docs)

    n = min(args.stages, len(jax.devices()))
    cfg = TransformerConfig(
        vocab=args.vocab, dim=args.dim, n_layers=2 * n, n_heads=4,
        n_kv_heads=2,
    )
    block, pre, post = llama_spmd(cfg, n)
    mesh = make_mesh(n, devices=jax.devices()[:n])
    pipe = SpmdGPipe(
        block, n, mesh, chunks=2, loss_fn=packed_cross_entropy_sum,
        pre=pre, post=post, checkpoint="except_last",
        loss_reduction="sum",
    )
    B = args.batch

    pk = D.pack_documents(docs, args.seq)
    packed = [
        (jax.tree_util.tree_map(jnp.asarray, x),
         jax.tree_util.tree_map(jnp.asarray, y))
        for x, y in D.packed_batches(pk, B)
    ]
    padded = [
        (jnp.asarray(x), jax.tree_util.tree_map(jnp.asarray, y))
        for x, y in D.padded_batches(docs, args.seq, B)
    ]
    out["pad_fraction"] = round(
        1.0 - n_real / (len(padded) * B * args.seq), 4
    )
    out["packed_blocks"] = pk.n_blocks
    out["padded_rows"] = len(docs)

    spec = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), packed[0][0]
    )
    params = pipe.place(pipe.init(jax.random.PRNGKey(0), spec))
    opt = optax.sgd(1e-3)
    step = pipe.make_train_step(opt, donate=False)
    opt_state = pipe.place_tree(opt.init(params))

    def run(batches, params, opt_state):
        # Warmup (compile) outside the timed window, then stream the
        # whole corpus --repeats times.
        x0, y0 = batches[0]
        l, p, s = step(params, opt_state, x0, y0)
        jax.block_until_ready(l)
        t0 = time.perf_counter()
        for _ in range(args.repeats):
            for x, y in batches:
                l, p, s = step(p, s, x, y)
        jax.block_until_ready(l)
        return time.perf_counter() - t0

    dt_packed = run(packed, params, opt_state)
    dt_padded = run(padded, params, opt_state)
    tok_s_packed = args.repeats * n_real / dt_packed
    tok_s_padded = args.repeats * n_real / dt_padded
    out["train"] = {
        "real_tokens": n_real,
        "packed_tok_s": round(tok_s_packed, 1),
        "padded_tok_s": round(tok_s_padded, 1),
        "speedup": round(tok_s_packed / tok_s_padded, 3),
        "bound": round(1.0 / (1.0 - out["pad_fraction"]), 3),
    }
    out["train"]["speedup_ok"] = out["train"]["speedup"] >= args.min_speedup

    # Matched per-document losses: packed blocks vs padded rows through
    # the SAME pipe.apply.
    max_seg = int(pk.segment_ids.max())
    packed_doc = []  # [n_blocks, max_seg] per-(row, segment) mean nll
    for x, y in packed:
        logits = pipe.apply(params, x)
        packed_doc.append(np.asarray(per_document_losses(
            logits, y, x["segment_ids"], max_seg
        )).reshape(B, max_seg))
    packed_doc = np.concatenate(packed_doc, 0)
    padded_doc = []  # per padded row: its document's mean nll
    for xt, yt in padded:
        lg = np.asarray(pipe.apply(params, xt), np.float32)
        logp = np.asarray(jax.nn.log_softmax(lg, -1))
        nll = -np.take_along_axis(
            logp, np.asarray(yt["labels"])[..., None], 2
        )[..., 0]
        w = np.asarray(yt["weights"])
        padded_doc.extend(
            (nll * w).sum(1) / np.maximum(w.sum(1), 1.0)
        )
    diffs = []
    for di, (r, off, _ln) in enumerate(pk.doc_locs):
        segnum = sum(
            1 for rr, oo, _ in pk.doc_locs if rr == r and oo <= off
        )
        diffs.append(abs(float(padded_doc[di]) - float(packed_doc[r, segnum - 1])))
    out["train"]["max_doc_loss_diff"] = float(max(diffs))
    out["train"]["loss_tol"] = LOSS_TOL
    out["train"]["equivalent"] = out["train"]["max_doc_loss_diff"] <= LOSS_TOL
    return out["train"]["equivalent"]


def _serving_side(args, out):
    from torchgpipe_tpu.layers import sequential_init
    from torchgpipe_tpu.models.transformer import TransformerConfig, llama
    from torchgpipe_tpu.serving import Engine

    cfg = TransformerConfig(
        vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2
    )
    params, _, _ = sequential_init(
        llama(cfg), jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((2, 8), jnp.int32),
    )

    def mix(seed):
        """Ragged bursty arrivals: bursts of 1-4 requests, prompt
        lengths 1..16, decode budgets 2..8."""
        r = np.random.RandomState(seed)
        bursts = []
        for _ in range(args.bursts):
            bursts.append([
                (r.randint(0, 64, (int(r.randint(1, 17)),)).astype(np.int32),
                 int(r.randint(2, 9)))
                for _ in range(int(r.randint(1, 5)))
            ])
        return bursts

    def drive(prefill_chunk):
        from torchgpipe_tpu.serving.metrics import ServingMetrics

        eng = Engine(
            cfg, params, num_slots=4, max_len=32,
            prefill_chunk=prefill_chunk,
        )
        # Warmup OUTSIDE the measured window: one request per ladder
        # bucket (served alone, so each bucket's program compiles now),
        # then fresh metrics — the comparison is steady-state TTFT/TPOT,
        # not compile stalls.
        for g in eng.prefill_buckets:
            eng.submit(np.arange(1, g + 1, dtype=np.int32), 2)
            eng.run()
        eng.metrics = ServingMetrics()
        for burst in mix(7):
            for prompt, new in burst:
                eng.submit(prompt, new)
            # Burstiness: a few engine iterations between bursts, so
            # later arrivals land in a busy engine.
            eng.run(max_steps=3)
        eng.run()
        snap = eng.metrics.snapshot()
        return {
            "programs": eng.program_count,
            "ttft_p50_ms": round(1e3 * (snap["ttft_p50"] or 0.0), 3),
            "ttft_p95_ms": round(1e3 * (snap["ttft_p95"] or 0.0), 3),
            "tpot_p50_ms": round(1e3 * (snap["tpot_p50"] or 0.0), 3),
            "tpot_p95_ms": round(1e3 * (snap["tpot_p95"] or 0.0), 3),
            "compile_stats": eng.compile_stats,
        }

    out["serving"] = {
        "ladder_off": drive(8),
        "ladder_on": drive((1, 2, 4, 8)),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=2)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--docs", type=int, default=32)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--bursts", type=int, default=8)
    ap.add_argument("--min-speedup", type=float, default=1.3)
    ap.add_argument("--gate", action="store_true",
                    help="fail (exit 1) when packed tokens/s misses "
                         "--min-speedup; equivalence always gates")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line (bench.py --packing)")
    args = ap.parse_args(argv)

    out: dict = {"bench": "packing", "platform": jax.devices()[0].platform}
    equivalent = _train_side(args, out)
    _serving_side(args, out)

    if args.json:
        print(json.dumps(out))
    else:
        print(json.dumps(out, indent=2))
    if not equivalent:
        print("FAIL: packed-vs-padded per-document losses diverge "
              f"(max diff {out['train']['max_doc_loss_diff']:.2e} > "
              f"{LOSS_TOL})")
        return 1
    if args.gate and not out["train"]["speedup_ok"]:
        print(f"FAIL: packed speedup {out['train']['speedup']} < "
              f"{args.min_speedup}")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
