"""Multi-process distributed pipeline training driver.

Reference: benchmarks/distributed/accuracy/main.py:106-204, 347-368 — one OS
process per rank joined over RPC (``--rank/--world/--master``), training a
sequential model split across ranks.  Here ranks join over
:class:`~torchgpipe_tpu.distributed.TcpTransport` (host-staged sockets, like
the reference's RPC transport); for single-host multi-device runs prefer the
in-process engine, and for pod-scale runs the SPMD engine (SURVEY.md §2.3).

Example (two shells)::

    python -m benchmarks.distributed_accuracy --rank 0 --world 2 \
        --master 127.0.0.1 --port-base 29500
    python -m benchmarks.distributed_accuracy --rank 1 --world 2 \
        --master 127.0.0.1 --port-base 29500
"""

from __future__ import annotations

import os
import time

import click
import jax
import jax.numpy as jnp

from benchmarks.common import hr_time, softmax_xent
from torchgpipe_tpu.balance import balance_by_time
from torchgpipe_tpu.distributed import (
    DistributedGPipe,
    DistributedGPipeDataLoader,
    TcpTransport,
)
from torchgpipe_tpu.layers import sequential_init
from torchgpipe_tpu.models import resnet50, vgg16
from torchgpipe_tpu.models.transformer import TransformerConfig, llama

def _mlp(classes):
    from torchgpipe_tpu.ops import dense, flatten, relu

    return [
        flatten(), dense(64, name="fc1"), relu("r1"),
        dense(64, name="fc2"), relu("r2"), dense(classes, name="fc3"),
    ]


MODELS = {
    # The reference's distributed accuracy bench trains sequential
    # resnet101/vgg16 over RPC ranks (benchmarks/distributed/accuracy/
    # {resnet,vgg}); scaled-width counterparts of both are here.
    "resnet50": lambda classes: resnet50(num_classes=classes, base_width=16),
    "vgg16": lambda classes: vgg16(
        num_classes=classes, base_width=16, head_width=256
    ),
    "llama-small": lambda classes: llama(
        TransformerConfig(vocab=classes, dim=128, n_layers=4, n_heads=4)
    ),
    "mlp": _mlp,  # tiny smoke-test model
}


@click.command()
@click.option("--rank", required=True, type=int)
@click.option("--world", required=True, type=int)
@click.option("--master", default="127.0.0.1")
@click.option("--port-base", default=29500)
@click.option("--model", "model_name", default="resnet50",
              type=click.Choice(sorted(MODELS)))
@click.option("--balance", default=None, type=str,
              help="comma-separated per-rank layer counts; default: profiled "
                   "balance_by_time on rank 0's layer costs (reference: "
                   "benchmarks/distributed/accuracy/main.py balance_by_time "
                   "fallback)")
@click.option("--chunks", default=4)
@click.option("--batch-size", default=32)
@click.option("--epochs", default=2)
@click.option("--steps", default=8)
@click.option("--classes", default=10)
@click.option("--image", default=32)
@click.option("--recv-timeout", default=None, type=float,
              help="bound every cross-rank receive; a dead peer surfaces as "
                   "a TimeoutError naming the missing channel instead of a "
                   "hang (leave unset when stage compile times are unknown)")
@click.option("--connect-timeout", default=120.0, type=float,
              help="rendezvous budget for dialing a peer's listener")
@click.option("--checkpoint-dir", default=None, type=str,
              help="crash recovery: each rank saves its partition params/"
                   "state here after every epoch and resumes from the last "
                   "completed epoch on restart (the reference's RPC mode "
                   "has neither failure detection nor recovery)")
def main(rank, world, master, port_base, model_name, balance, chunks,
         batch_size, epochs, steps, classes, image, recv_timeout,
         connect_timeout, checkpoint_dir):
    layers = MODELS[model_name](classes)
    workers = [f"rank{r}" for r in range(world)]
    # Each rank listens on port_base + rank; peers dial the master host.
    addresses = {f"rank{r}": (master, port_base + r) for r in range(world)}
    addresses[f"rank{rank}"] = ("0.0.0.0", port_base + rank)
    transport = TcpTransport(
        f"rank{rank}", addresses, connect_timeout=connect_timeout
    )

    if model_name == "llama-small":
        x0 = jnp.zeros((batch_size, 64), jnp.int32)

        def make_batch(key):
            # Next-token LM objective: labels are the inputs shifted by one.
            tokens = jax.random.randint(key, x0.shape, 0, classes)
            return tokens, jnp.roll(tokens, -1, axis=1)
    else:
        shape = (
            (batch_size, image, image, 3)
            if model_name in ("resnet50", "vgg16")
            else (batch_size, 16)
        )
        x0 = jnp.zeros(shape, jnp.float32)

        def make_batch(key):
            kx, ky = jax.random.split(key)
            return (
                jax.random.normal(kx, x0.shape),
                jax.random.randint(ky, (batch_size,), 0, classes),
            )
    in_spec = jax.ShapeDtypeStruct(x0.shape, x0.dtype)

    if balance:
        balance = [int(v) for v in balance.split(",")]
    elif rank == 0:
        # Profile on rank 0 only and broadcast: wall-clock profiling on every
        # rank independently could disagree on the balance and deadlock the
        # pipe with mismatched stage ownership.
        params0, states0, _ = sequential_init(
            layers, jax.random.PRNGKey(0), in_spec
        )
        balance = balance_by_time(
            world, layers, params0, states0, x0, timeout=0.5
        )
        print(f"[rank 0] profiled balance: {balance}", flush=True)
        for r in range(1, world):
            transport.send(f"rank{r}", "balance", 0, balance)
    else:
        balance = list(transport.mailbox.get("balance", 0, timeout=600))

    pipe = DistributedGPipe(
        layers, rank, workers, balance, chunks=chunks,
        transport=transport, mailbox=transport.mailbox,
        recv_timeout=recv_timeout,
    )
    params, state = pipe.init(jax.random.PRNGKey(0), in_spec)

    # Crash recovery: each rank persists ITS partition after every epoch;
    # on restart, resume from the last epoch every rank completed.  The
    # checkpoint records (model, world, balance, ...) and every leaf shape
    # is validated against the fresh init, so a restart with a different
    # partitioning fails loudly instead of loading the wrong weights.
    ckpt_path = (
        os.path.join(checkpoint_dir, f"rank{rank}.npz")
        if checkpoint_dir
        else None
    )
    ckpt_meta = (
        f"{model_name}|world={world}|rank={rank}|balance={balance}|"
        f"classes={classes}|image={image}|chunks={chunks}"
    )
    start_epoch = 0
    if ckpt_path and os.path.exists(ckpt_path):
        params, state, start_epoch = _load_rank_checkpoint(
            ckpt_path, params, state, ckpt_meta, checkpoint_dir
        )
        print(f"[rank {rank}] resumed from epoch {start_epoch}", flush=True)
    if checkpoint_dir:
        os.makedirs(checkpoint_dir, exist_ok=True)
        # Every rank reports its epoch to rank 0, which broadcasts either
        # the agreed value or an abort sentinel — so a torn checkpoint set
        # (crash between per-rank saves) makes EVERY rank exit with the
        # same didactic message instead of some ranks hanging in the pipe
        # waiting for a peer that aborted.
        if rank == 0:
            seen = {0: start_epoch}
            for r in range(1, world):
                seen[r] = int(
                    transport.mailbox.get("epoch_report", r, timeout=600)
                )
            torn = len(set(seen.values())) != 1
            agreed = -1 if torn else start_epoch
            for r in range(1, world):
                transport.send(f"rank{r}", "resume_epoch", 0, agreed)
            if torn:
                raise SystemExit(
                    f"[rank 0] checkpoint epochs disagree across ranks "
                    f"({seen}); delete {checkpoint_dir} and restart from "
                    "scratch"
                )
        else:
            transport.send("rank0", "epoch_report", rank, start_epoch)
            agreed = int(transport.mailbox.get("resume_epoch", 0, timeout=600))
            if agreed < 0:
                raise SystemExit(
                    f"[rank {rank}] checkpoint epochs disagree across "
                    f"ranks; delete {checkpoint_dir} and restart from "
                    "scratch"
                )

    # Only rank 0 feeds data (the loader ships targets to the last rank).
    data = (
        [make_batch(jax.random.PRNGKey(100 + s)) for s in range(steps)]
        if rank == 0
        else None
    )
    loader = DistributedGPipeDataLoader(
        data, rank, workers,
        transport=transport, mailbox=transport.mailbox, num_batches=steps,
        recv_timeout=recv_timeout,
    )

    t0 = time.time()
    for epoch in range(start_epoch, epochs):
        for step, (xb, yb) in enumerate(loader):
            key = jax.random.fold_in(jax.random.PRNGKey(7), epoch * steps + step)
            outs = pipe.forward(params, state, xb, rng=key)
            if pipe.is_last:
                loss, gys, _ = pipe.loss_grads(outs, yb, softmax_xent)
                grads, state = pipe.backward(gys)
                print(
                    f"{hr_time(time.time() - t0)} | epoch {epoch + 1} "
                    f"step {step + 1}: loss {float(loss):.4f}",
                    flush=True,
                )
            else:
                grads, state = pipe.backward(None)
            params = jax.tree_util.tree_map(
                lambda p, g: p - 0.05 * g, params, list(grads)
            )
        if ckpt_path:
            _save_rank_checkpoint(
                ckpt_path, params, state, epoch + 1, ckpt_meta
            )
    transport.close()
    print(f"[rank {rank}] done", flush=True)


def _save_rank_checkpoint(path, params, state, epoch: int, meta: str) -> None:
    """Atomically persist this rank's partition (write-then-rename), tagged
    with the run configuration so a mismatched restart is caught on load."""
    import numpy as np

    from torchgpipe_tpu.utils.serialization import save

    leaves_p = jax.tree_util.tree_leaves(params)
    leaves_s = jax.tree_util.tree_leaves(state)
    payload = {f"p{i}": np.asarray(l) for i, l in enumerate(leaves_p)}
    payload.update({f"s{i}": np.asarray(l) for i, l in enumerate(leaves_s)})
    payload["epoch"] = np.asarray(epoch)
    payload["meta"] = np.asarray(meta)
    tmp = path + ".tmp.npz"  # savez appends .npz unless already suffixed
    save(tmp, payload)
    os.replace(tmp, path)


def _load_rank_checkpoint(path, params, state, meta: str, ckpt_dir: str):
    """Restore params/state into the freshly-initialized tree structure,
    validating run configuration and every leaf shape/dtype first."""
    from torchgpipe_tpu.utils.serialization import load

    d = load(path)
    if str(d.get("meta")) != meta:
        raise SystemExit(
            f"checkpoint {path} was written by a different run "
            f"configuration:\n  saved: {d.get('meta')}\n  now:   {meta}\n"
            f"delete {ckpt_dir} and restart from scratch"
        )
    init_p = jax.tree_util.tree_leaves(params)
    init_s = jax.tree_util.tree_leaves(state)
    want = {f"p{i}" for i in range(len(init_p))}
    want |= {f"s{i}" for i in range(len(init_s))}
    have = set(d) - {"epoch", "meta"}
    if have != want:
        raise SystemExit(
            f"checkpoint {path} leaf set mismatch (saved {len(have)}, "
            f"expected {len(want)}); delete {ckpt_dir} and restart"
        )
    leaves_p = [d[f"p{i}"] for i in range(len(init_p))]
    leaves_s = [d[f"s{i}"] for i in range(len(init_s))]
    for got, ref in zip(leaves_p + leaves_s, init_p + init_s):
        if got.shape != ref.shape or got.dtype != ref.dtype:
            raise SystemExit(
                f"checkpoint {path} leaf {got.shape}/{got.dtype} does not "
                f"match the model's {ref.shape}/{ref.dtype}; delete "
                f"{ckpt_dir} and restart"
            )
    params = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params), leaves_p
    )
    state = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(state), leaves_s
    )
    return params, state, int(d["epoch"])


if __name__ == "__main__":
    main()
