"""Llama pipeline speed benchmark — the BASELINE.json north-star config
("Llama-3-8B as nn.Sequential of transformer blocks, 8-stage pipeline").

Two engines over the same model family:

* ``--engine mpmd`` (default): :class:`torchgpipe_tpu.gpipe.GPipe` over the
  flat ``llama()`` layer list — heterogeneous embed/blocks/head stages, any
  balance.
* ``--engine spmd``: :class:`torchgpipe_tpu.spmd.SpmdGPipe` — the whole
  schedule as one compiled program on a ``pp`` mesh axis (needs
  ``n_stages`` devices and ``n_layers % n_stages == 0``).

``--preset llama3-8b`` selects the real Llama-3-8B shape (dim 4096, 32
blocks, 32 heads / 8 KV heads, vocab 128256); the default preset is a
scaled-down shape so the grid runs on small hosts/chips.  The causal-LM
objective shifts tokens by one position.
"""

from __future__ import annotations

import click
import jax
import jax.numpy as jnp

from benchmarks.common import even_balance, run_speed
from torchgpipe_tpu.gpipe import GPipe
from torchgpipe_tpu.models.transformer import (
    TransformerConfig,
    cross_entropy,
    llama,
)

# name -> (n_stages, batch, chunks)
EXPERIMENTS = {
    "pipeline-1": (1, 8, 4),
    "pipeline-2": (2, 16, 4),
    "pipeline-4": (4, 32, 8),
    "pipeline-8": (8, 64, 8),
}

PRESETS = {
    # dim, n_layers, n_heads, n_kv_heads, vocab, mlp_ratio.
    # TransformerConfig.mlp_hidden applies the SwiGLU 2/3 factor, so
    # hidden = 2*ratio*dim/3 (rounded to 128): the published Llama hidden
    # sizes need ratio 5.25 (8B: 14336 = 2*5.25*4096/3) and 6.0
    # (3.2-1B: 8192 = 2*6*2048/3).
    "tiny": (256, 8, 8, 4, 1024, 4.0),
    # ~200M params: big enough for meaningful attention/window timings at
    # long seq, small enough to compile and fit beside HBM co-tenants.
    "small": (1024, 12, 16, 8, 32000, 4.0),
    "1b": (2048, 16, 32, 8, 128256, 6.0),
    "llama3-8b": (4096, 32, 32, 8, 128256, 5.25),
}


def causal_lm_loss(out, tokens):
    # Shifted causal objective: predict token t+1 from prefix <= t.
    logits = out[:, :-1, :]
    labels = tokens[:, 1:]
    return cross_entropy(logits, labels)


@click.command()
@click.argument("experiment", type=click.Choice(sorted(EXPERIMENTS)))
@click.option("--preset", type=click.Choice(sorted(PRESETS)), default="tiny")
@click.option("--engine", type=click.Choice(["mpmd", "spmd"]), default="mpmd")
@click.option("--seq", default=1024)
@click.option("--batch", default=None, type=int)
@click.option("--epochs", default=3)
@click.option("--steps", default=10)
@click.option("--bf16/--no-bf16", default=True,
              help="bfloat16 block compute (TransformerConfig.dtype)")
@click.option("--checkpoint", default="except_last",
              type=click.Choice(["always", "except_last", "never"]))
@click.option("--moe-experts", default=0,
              help="replace the dense MLP with a top-k routed MoE of this "
                   "many experts (0 = dense)")
@click.option("--moe-top-k", default=2)
@click.option("--ep", default=1,
              help="expert-parallel mesh axis size (spmd engine; needs "
                   "n_stages*dp*ep*tp devices)")
@click.option("--tp", default=1,
              help="tensor-parallel mesh axis size (spmd engine; needs "
                   "n_stages*dp*ep*tp devices)")
@click.option("--dp", default=1,
              help="data-parallel mesh axis size (spmd engine)")
@click.option("--schedule",
              type=click.Choice(["fill_drain", "1f1b", "interleaved", "zb"]),
              default="fill_drain",
              help="spmd engine schedule: 1f1b runs PipeDream-flush with "
                   "O(n) activation memory; interleaved adds Megatron "
                   "virtual pipeline stages (--virtual-stages chunks per "
                   "device, ~v x smaller bubble); zb splits the backward "
                   "into dx-only B cells + weight-grad W cells that "
                   "back-fill bubbles (checkpoint never|always)")
@click.option("--virtual-stages", default=2,
              help="model chunks per device for --schedule interleaved")
@click.option("--fsdp/--no-fsdp", default=False,
              help="ZeRO-3-style parameter sharding over the dp axis "
                   "(spmd engine; needs --dp > 1)")
@click.option("--moe-dispatch",
              type=click.Choice(["auto", "dense", "sparse", "dropless"]),
              default="auto",
              help="MoE token dispatch: capacity-based one-hot einsums "
                   "(dense), sort-based scatter/gather (sparse), or "
                   "capacity-free ragged grouped matmuls (dropless; needs "
                   "local experts, i.e. --ep 1)")
@click.option("--moe-router", type=click.Choice(["topk", "expert_choice"]),
              default="topk",
              help="routing direction: tokens pick experts (topk) or "
                   "experts pick tokens (expert_choice — perfectly "
                   "balanced by construction; needs --ep 1)")
@click.option("--fused-ce/--no-fused-ce", default=False,
              help="fuse the LM head into a chunked-vocab cross-entropy "
                   "loss layer (both engines): the [tokens, vocab] logits "
                   "are never materialized — the big-vocab memory fix "
                   "(needs --tp 1; dense model only on mpmd)")
@click.option("--attn-window", default=None, type=int,
              help="sliding-window attention: attend iff 0 <= qpos - kpos "
                   "< N (Mistral-style); compute in the flash kernels "
                   "scales with the window, not the sequence length")
@click.option("--autotune/--no-autotune", default=False,
              help="run the static step autotuner (torchgpipe_tpu.tune) "
                   "before timing: sweeps remat policy x micro-batch "
                   "count x CE chunk, prints the frontier, and times the "
                   "best HBM-feasible candidate instead of the CLI flags' "
                   "checkpoint/chunks (spmd engine, fill_drain)")
@click.option("--hbm-budget-gib", default=15.75,
              help="per-chip HBM budget for --autotune feasibility "
                   "(default: the v5e AOT limit)")
def main(experiment, preset, engine, seq, batch, epochs, steps, bf16,
         checkpoint, moe_experts, moe_top_k, ep, tp, dp, schedule,
         virtual_stages, fsdp, moe_dispatch, moe_router, fused_ce,
         attn_window, autotune, hbm_budget_gib):
    n, bsz, chunks = EXPERIMENTS[experiment]
    bsz = batch or bsz
    dim, n_layers, n_heads, n_kv, vocab, mlp_ratio = PRESETS[preset]
    cfg = TransformerConfig(
        vocab=vocab, dim=dim, n_layers=n_layers, n_heads=n_heads,
        n_kv_heads=n_kv, mlp_ratio=mlp_ratio,
        dtype=jnp.bfloat16 if bf16 else jnp.float32,
        tp_axis="tp" if tp > 1 else None,
        attn_window=attn_window,
    )
    if ep > 1 and engine != "spmd":
        raise click.UsageError(
            "--ep needs the spmd engine (expert-parallel mesh axis); the "
            "mpmd engine runs all experts locally"
        )
    if ep > 1 and not moe_experts:
        raise click.UsageError("--ep without --moe-experts has no effect")
    if tp > 1 and engine != "spmd":
        raise click.UsageError(
            "--tp needs the spmd engine (tensor-parallel mesh axis)"
        )
    if (dp > 1 or fsdp) and engine != "spmd":
        raise click.UsageError("--dp/--fsdp need the spmd engine")
    if schedule != "fill_drain" and engine != "spmd":
        raise click.UsageError(
            "--schedule selects the spmd engine's schedule; the mpmd "
            "engine takes GPipe(schedule=...) via its own driver path"
        )
    if fsdp and dp <= 1:
        raise click.UsageError("--fsdp shards over the dp lanes: pass --dp > 1")
    if fused_ce and engine == "mpmd" and moe_experts:
        raise click.UsageError("--fused-ce with the mpmd engine supports "
                               "the dense model only")
    if fused_ce and tp > 1:
        raise click.UsageError("--fused-ce uses local head weights; with "
                               "--tp use the vocab-parallel CE path instead")
    moe = None
    if moe_experts:
        from torchgpipe_tpu.models.moe import MoEConfig

        moe = MoEConfig(
            n_experts=moe_experts, top_k=moe_top_k,
            ep_axis="ep" if ep > 1 else None,
            dispatch=moe_dispatch,
            router=moe_router,
        )
    x = jnp.zeros((bsz, seq), jnp.int32)

    if autotune and (engine != "spmd" or schedule != "fill_drain"):
        raise click.UsageError(
            "--autotune models the spmd engine's fill_drain schedule "
            "(tune_step); pass --engine spmd without --schedule"
        )
    if engine == "spmd":
        tput = _run_spmd(
            cfg, n, chunks, x, epochs, steps, checkpoint, experiment, moe,
            ep, tp, dp, fsdp, schedule,
            virtual_stages if schedule == "interleaved" else 1,
            fused_ce, autotune=autotune, hbm_budget_gib=hbm_budget_gib,
        )
    elif fused_ce:
        # Headless model + parametric chunked-CE loss layer: the head
        # matmul and cross-entropy fuse, [tokens, vocab] logits never
        # materialize (GPipe.value_and_grad_with_loss_params).
        from benchmarks.common import run_epoch_loop
        from torchgpipe_tpu.models.transformer import chunked_lm_loss

        layers = llama(cfg, head=False)
        model = GPipe(
            layers, even_balance(len(layers), n), chunks=chunks,
            checkpoint=checkpoint,
        )
        loss_layer = chunked_lm_loss(cfg)
        in_spec = jax.ShapeDtypeStruct(x.shape, x.dtype)
        params, state = model.init(jax.random.PRNGKey(0), in_spec)
        loss_params, _ = loss_layer.init(jax.random.PRNGKey(2), in_spec)
        carry = {"params": params, "loss_params": loss_params,
                 "state": state}
        inputs, targets = x[:, :-1], x[:, 1:]
        rng = jax.random.PRNGKey(1)

        def step_fn(global_step):
            key = jax.random.fold_in(rng, global_step)
            loss, grads, lgrads, new_state, _ = (
                model.value_and_grad_with_loss_params(
                    carry["params"], carry["loss_params"], carry["state"],
                    inputs, targets, loss_layer, rng=key,
                )
            )
            carry["params"] = tuple(
                jax.tree_util.tree_map(lambda p, g: p - 1e-4 * g, ps, gs)
                for ps, gs in zip(carry["params"], grads)
            )
            carry["loss_params"] = jax.tree_util.tree_map(
                lambda p, g: p - 1e-4 * g, carry["loss_params"], lgrads
            )
            carry["state"] = new_state
            return loss, carry["params"]

        tput = run_epoch_loop(
            step_fn, x.shape[0], epochs=epochs, steps_per_epoch=steps,
            label=experiment,
        )

        from benchmarks.common import (
            analytic_flops, distinct_chips, print_mfu,
        )
        from torchgpipe_tpu.layers import sequential_apply

        flat_p = [p for stage in params for p in stage]
        flat_s = [s for stage in state for s in stage]

        def _plain_step(fp, lp, xx, yy):
            def loss_of(ps):
                fp2, lp2 = ps
                out, _ = sequential_apply(
                    layers, fp2, flat_s, xx, rng=rng, train=True
                )
                l, _ = loss_layer.apply(lp2, (), (out, yy), rng=None,
                                        train=True)
                return l

            return jax.value_and_grad(loss_of)((fp, lp))

        print_mfu(
            lambda: analytic_flops(_plain_step, flat_p, loss_params,
                                   inputs, targets),
            tput, x.shape[0], experiment, n_chips=distinct_chips(model),
            device=model.devices[0],
        )
    else:
        if moe is not None:
            from torchgpipe_tpu.models.moe import llama_moe

            layers = llama_moe(cfg, moe)
        else:
            layers = llama(cfg)
        model = GPipe(
            layers, even_balance(len(layers), n), chunks=chunks,
            checkpoint=checkpoint,
        )

        def after(params, state):
            if moe is None:
                return
            # Router balance of the first MoE block on the final batch's
            # embeddings (layer 0 = token_embedding on stage 0).
            del state
            h, _ = layers[0].apply(params[0][0], (), x[:, :-1],
                                   rng=None, train=False)
            _print_router_stats(params, h, moe)

        tput = run_speed(
            model, x, x, causal_lm_loss,
            epochs=epochs, steps_per_epoch=steps, label=experiment,
            after=after,
        )
    kind = f"moe{moe_experts}" if moe_experts else "dense"
    print(
        f"FINAL | llama-speed {experiment} [{preset}, {engine}, {kind}]: "
        f"{tput:.1f} samples/sec"
    )


def _print_router_stats(params, h, moe):
    """Balance metrics of the first router found in ``params`` against
    hidden states ``h`` (router_stats: load/importance/Switch penalty)."""
    from torchgpipe_tpu.models.moe import find_routers, router_stats

    routers = find_routers(params)
    if not routers:
        return
    load, imp, bal = router_stats(routers[0], h, moe)
    # The hidden states are the token EMBEDDINGS of the last batch — an
    # input-distribution proxy for block 0's true router input (which sees
    # normed post-attention states); say so in the output.
    print(
        f"router[block0, embedding-proxy] | balance={float(bal):.3f} "
        f"(1.0=perfect) "
        f"load[min/max]={float(load.min()):.3f}/{float(load.max()):.3f} "
        f"importance[min/max]={float(imp.min()):.3f}/{float(imp.max()):.3f}",
        flush=True,
    )


def _run_spmd(cfg, n, chunks, x, epochs, steps, checkpoint, label, moe=None,
              ep=1, tp=1, dp=1, fsdp=False, schedule="fill_drain",
              virtual_stages=1, fused_ce=False, autotune=False,
              hbm_budget_gib=15.75):
    from benchmarks.common import run_epoch_loop
    from torchgpipe_tpu.models.transformer import llama_spmd
    from torchgpipe_tpu.spmd import SpmdGPipe, make_mesh

    # Interleaved: the model is cut into n*v thinner blocks (device j owns
    # chunks c*n+j), so the block builder sees the virtual stage count.
    n_blocks = n * virtual_stages
    if moe is not None:
        from torchgpipe_tpu.models.moe import llama_moe_spmd

        block, pre, post = llama_moe_spmd(cfg, moe, n_blocks)
    else:
        block, pre, post = llama_spmd(cfg, n_blocks)
    mesh = make_mesh(n, dp=dp, ep=ep, tp=tp)
    if fused_ce:
        # Chunked-vocab CE loss layer replaces the lm_head post: the
        # [tokens, vocab] logits are never materialized (the big-vocab
        # memory fix; see models.transformer.chunked_lm_loss).
        from torchgpipe_tpu.models.transformer import chunked_lm_loss

        loss_fn, post = chunked_lm_loss(cfg), None
    else:
        loss_fn = cross_entropy
    pipe = SpmdGPipe(
        block, n, mesh, chunks=chunks, loss_fn=loss_fn,
        pre=pre, post=post, checkpoint=checkpoint,
        dp_axis="dp" if dp > 1 else None,
        ep_axis="ep" if ep > 1 else None,
        tp_axis="tp" if tp > 1 else None,
        fsdp=fsdp,
        schedule=schedule,
        virtual_stages=virtual_stages,
    )
    # SpmdGPipe shards data over the mesh; the causal shift happens on the
    # host so inputs/targets ride the same sharding specs.
    inputs, targets = x[:, :-1], x[:, 1:]
    if autotune:
        # Static sweep BEFORE any compile: pick the point on the
        # recompute/memory curve instead of the CLI's checkpoint/chunks
        # (the hand-walked rung replacement; docs/tuning.md).
        from torchgpipe_tpu import tune

        report = tune.tune_step(
            pipe, jax.ShapeDtypeStruct(inputs.shape, inputs.dtype),
            hbm_budget_bytes=int(hbm_budget_gib * 2 ** 30),
        )
        print(report.table(), flush=True)
        best = report.best
        if best is None:
            raise SystemExit(
                "autotune: no candidate fits the "
                f"{hbm_budget_gib} GiB budget (see the table above)"
            )
        print(
            f"autotune | timing checkpoint={best.checkpoint!r} "
            f"policy={best.policy or '-'} chunks={best.chunks}"
            + (f" ce_chunk={best.ce_chunk}" if best.ce_chunk else ""),
            flush=True,
        )
        pipe = tune.apply_candidate(pipe, best)
    carry = {
        "params": pipe.init(
            jax.random.PRNGKey(0),
            jax.ShapeDtypeStruct(inputs.shape, inputs.dtype),
        )
    }

    # Batches stream through the double-buffered sharding-aware
    # prefetcher: batch k+1's host→device copy (committed to the mesh's
    # data sharding) overlaps step k's compute — the hot path consumes
    # utils.data.prefetch_to_pipe instead of re-uploading per step.
    from itertools import repeat

    from torchgpipe_tpu.utils.data import prefetch_to_pipe

    batches = prefetch_to_pipe(repeat((inputs, targets)), pipe, size=2)

    def step_fn(global_step):
        del global_step
        xb, yb = next(batches)
        loss, grads = pipe.train_step(carry["params"], xb, yb)
        carry["params"] = jax.tree_util.tree_map(
            lambda p, g: p - 1e-4 * g, carry["params"], grads
        )
        return loss, carry["params"]

    tput = run_epoch_loop(
        step_fn, x.shape[0], epochs=epochs, steps_per_epoch=steps, label=label
    )

    # MFU for the spmd engine too (same convention as the mpmd branches:
    # the numerator is the UN-pipELINED model's fwd+loss+bwd, costed from
    # a plain sequential step over the stacked block params).  Configs
    # whose block graph needs mesh collectives at trace time (tp/sp/ep)
    # fail the plain lowering — analytic_flops returns None there and
    # print_mfu stays silent rather than publishing a wrong denominator.
    from benchmarks.common import analytic_flops, print_mfu

    def _plain_step(ps):
        def loss_of(ps):
            h = inputs
            if pre is not None:
                h, _ = pre.apply(ps["pre"], (), h, rng=None, train=True)

            def body(hh, bp):
                out, _ = block.apply(bp, (), hh, rng=None, train=True)
                return out, None

            h, _ = jax.lax.scan(body, h, ps["blocks"])
            if post is not None:
                h, _ = post.apply(ps["post"], (), h, rng=None, train=True)
            if "loss" in ps:
                l, _ = loss_fn.apply(
                    ps["loss"], (), (h, targets), rng=None, train=True
                )
            else:
                l = loss_fn(h, targets)
            return l

        return jax.value_and_grad(loss_of)(ps)

    print_mfu(
        lambda: analytic_flops(_plain_step, carry["params"]),
        tput, x.shape[0], label,
        n_chips=int(mesh.devices.size),
        device=mesh.devices.flat[0],
    )
    if moe is not None and pre is not None:
        # Router balance of stage 0's first MoE block on the final batch.
        stage0 = jax.tree_util.tree_map(
            lambda a: a[0], carry["params"]["blocks"]
        )
        h, _ = pre.apply(carry["params"]["pre"], (), inputs,
                         rng=None, train=False)
        _print_router_stats(stage0, h, moe)
    return tput


if __name__ == "__main__":
    main()
