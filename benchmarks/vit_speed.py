"""Sequential ViT speed benchmark.

No reference counterpart (the reference zoo is conv-only); this driver
mirrors the zoo's speed-driver shape (reference:
benchmarks/resnet101-speed/main.py:21-77 — experiment table, fake data,
samples/sec) for the transformer vision model, where the MXU fraction
is far higher than the conv nets': one patchify matmul + dense
attention/MLP blocks.
"""

from __future__ import annotations

import click
import jax
import jax.numpy as jnp

from benchmarks.common import bf16_option, build_gpipe, run_speed, softmax_xent
from torchgpipe_tpu.models import vit

# name -> (n_stages, batch, chunks)
EXPERIMENTS = {
    "baseline": (1, 128, 1),
    "pipeline-1": (1, 256, 4),
    "pipeline-2": (2, 512, 8),
    "pipeline-4": (4, 1024, 16),
    "pipeline-8": (8, 2048, 32),
}


@click.command()
@click.argument("experiment", type=click.Choice(sorted(EXPERIMENTS)))
@click.option("--epochs", default=3)
@click.option("--steps", default=10)
@click.option("--image", default=224)
@click.option("--patch", default=16)
@click.option("--dim", default=384, help="ViT-S/16 width")
@click.option("--depth", default=12)
@click.option("--heads", default=6)
@click.option("--batch", default=None, type=int)
@bf16_option
def main(experiment, epochs, steps, image, patch, dim, depth, heads,
         batch, bf16):
    n, bsz, chunks = EXPERIMENTS[experiment]
    bsz = batch or bsz
    layers = vit(
        image_size=image, patch_size=patch, dim=dim, depth=depth,
        n_heads=heads, num_classes=1000,
    )
    model = build_gpipe(layers, None, n, chunks, "except_last", bf16=bf16)
    x = jnp.zeros((bsz, image, image, 3), jnp.float32)
    y = jax.random.randint(jax.random.PRNGKey(0), (bsz,), 0, 1000)
    tput = run_speed(
        model, x, y, softmax_xent,
        epochs=epochs, steps_per_epoch=steps, label=experiment,
    )
    print(f"FINAL | vit-speed {experiment}: {tput:.1f} samples/sec")


if __name__ == "__main__":
    main()
