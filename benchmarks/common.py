"""Shared benchmark-driver plumbing.

Mirrors the reference drivers' structure (timed epochs over synthetic data,
``HH:MM:SS | throughput`` progress lines — reference:
benchmarks/amoebanetd-speed/main.py:121-138, 235-265) on the TPU-native
engine: one :func:`run_speed` / :func:`run_memory` pair serves every model
family.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from torchgpipe_tpu.gpipe import GPipe
from torchgpipe_tpu.layers import Layer


def hr_time(seconds: float) -> str:
    m, s = divmod(int(seconds), 60)
    h, m = divmod(m, 60)
    return f"{h:02d}:{m:02d}:{s:02d}"


def even_balance(n_layers: int, n_stages: int) -> List[int]:
    base, rem = divmod(n_layers, n_stages)
    return [base + (1 if j >= n_stages - rem else 0) for j in range(n_stages)]


def softmax_xent(out, tgt):
    logits = out.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits.reshape(-1, logits.shape[-1]))
    return -jnp.mean(logp[jnp.arange(logp.shape[0]), tgt.reshape(-1)])


def mse(out, tgt):
    return jnp.mean((out.astype(jnp.float32) - tgt) ** 2)


def build_gpipe(
    layers: Sequence[Layer],
    balance: Optional[Sequence[int]],
    n_stages: int,
    chunks: int,
    checkpoint: str,
    devices=None,
    tracer=None,
    bf16: bool = False,
    deferred_batch_norm: bool = False,
) -> GPipe:
    if balance is None:
        balance = even_balance(len(layers), n_stages)
    return GPipe(
        list(layers), balance, chunks=chunks, checkpoint=checkpoint,
        devices=devices, tracer=tracer,
        compute_dtype=jnp.bfloat16 if bf16 else None,
        deferred_batch_norm=deferred_batch_norm,
    )


def bf16_option(fn):
    """Shared ``--bf16`` click option: bfloat16 compute with f32 masters
    (torchgpipe_tpu.precision; no reference counterpart — the reference
    trains float32 only)."""
    import click

    return click.option(
        "--bf16/--no-bf16", default=False,
        help="bfloat16 compute, float32 masters + norm statistics",
    )(fn)


def run_epoch_loop(
    step_fn: Callable,
    batch: int,
    *,
    epochs: int,
    steps_per_epoch: int,
    skip_epochs: int = 1,
    label: str = "experiment",
) -> float:
    """Timed training epochs over ``step_fn(global_step) -> (loss, block_on)``;
    returns steady-state samples/sec.

    Reference loop shape: benchmarks/amoebanetd-speed/main.py:235-265
    (first epoch discarded as warm-up/compile).  With a single epoch nothing
    can be discarded, so the warm-up epoch is measured rather than reporting
    zero.
    """
    skip = skip_epochs if epochs > skip_epochs else 0
    throughputs = []
    t_start = time.time()
    for epoch in range(epochs):
        t0 = time.time()
        for step in range(steps_per_epoch):
            loss, block_on = step_fn(epoch * steps_per_epoch + step)
        jax.block_until_ready(block_on)
        dt = time.time() - t0
        tput = batch * steps_per_epoch / dt
        if epoch >= skip:
            throughputs.append(tput)
        print(
            f"{hr_time(time.time() - t_start)} | {label} | epoch {epoch + 1}: "
            f"{tput:.1f} samples/sec, loss {float(loss):.4f}"
            + ("  (warm-up)" if epoch < skip else ""),
            flush=True,
        )
    return sum(throughputs) / max(1, len(throughputs))


def analytic_flops(step: Callable, *args) -> Optional[float]:
    """Model FLOPs of one ``step(*args)`` call from XLA's HLO cost
    analysis.  ``args`` may be arrays or ``ShapeDtypeStruct``s — lowering
    happens from abstract avals (``lower()`` only traces, no compile, and
    nothing executes).  Falls back to lowering for the host CPU client
    when the accelerator client doesn't implement ``cost_analysis`` (the
    axon TPU tunnel returns ``None``; analytic model FLOPs are
    platform-independent).  Returns ``None`` when neither client can
    cost the program."""
    specs = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), args
    )

    def flops_of(lowered) -> Optional[float]:
        cost = lowered.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if cost is None:
            return None
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None

    try:
        got = flops_of(jax.jit(step).lower(*specs))
        if got is not None:
            return got
    except Exception:
        pass
    try:
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            return flops_of(jax.jit(step).lower(*specs))
    except Exception:
        return None


def print_mfu(
    step_flops, tput: float, batch: int, label: str, n_chips: int = 1,
    device=None,
) -> None:
    """One ``label | mfu …`` line when the default device has a published
    bf16 peak (``torchgpipe_tpu.utils.hw``); silent on host-CPU runs.

    ``step_flops`` is the per-step model FLOPs, or a zero-arg callable
    producing them — the callable is only invoked on a known chip, so
    host-CPU runs never pay the lowering.  ``tput`` is AGGREGATE
    samples/sec; ``n_chips`` divides the peak so a pipeline spanning n
    chips is graded against n chips' worth of FLOP/s (matching
    ``bench.py``'s ``n_chips * peak`` denominator).

    MFU convention matches ``bench.py``: the numerator is the
    UN-pipelined model's analytic work (fwd + loss + bwd, no recompute),
    so activation rematerialization counts *against* utilization rather
    than inflating it.  An MFU above 1.0 is physically impossible —
    the backend cannot have executed every dispatched program before
    ``block_until_ready`` returned (observed once on the axon tunnel's
    warm executable cache) — so it is flagged as invalid rather than
    printed as a result, mirroring ``bench.py``'s refusal to publish
    impossible numbers.

    ``device`` is the device the timed programs actually ran on (a model
    placed on explicit devices — e.g. a host-CPU debug run on a
    TPU-attached machine — must not be graded against the default
    device's peak); defaults to ``jax.devices()[0]``."""
    from torchgpipe_tpu.utils.hw import chip_peak_bf16_flops

    peak = chip_peak_bf16_flops(
        jax.devices()[0] if device is None else device
    )
    if peak is None or tput <= 0:
        return
    if callable(step_flops):
        # A broken FLOPs-costing path may only cost the mfu line, never
        # the already-printed throughput result.
        try:
            step_flops = step_flops()
        except Exception:
            return
    if step_flops is None:
        return
    mfu = step_flops * tput / batch / (max(1, n_chips) * peak)
    if mfu > 1.0:
        print(
            f"MFU   | {label}: INVALID ({100 * mfu:.1f}% > 100% is "
            "physically impossible — the timed loop's programs cannot "
            "all have executed; do not publish this run)",
            flush=True,
        )
        return
    print(
        f"MFU   | {label}: {100 * mfu:.2f}% "
        f"(analytic model FLOPs {step_flops:.3e}/step over "
        f"{max(1, n_chips)}x {peak:.3g} peak bf16 FLOP/s)",
        flush=True,
    )


def distinct_chips(model: GPipe) -> int:
    """Number of distinct devices the model's stages are placed on."""
    return len({(d.platform, d.id) for d in model.devices})


def sequential_step_flops(model: GPipe, params, state, x, y,
                          loss_fn: Callable, rng) -> Optional[float]:
    """Analytic FLOPs of the equivalent un-pipelined training step of a
    :class:`GPipe` model (the MFU numerator — see :func:`print_mfu`).
    Losses returning ``(loss, aux)`` are reduced to the scalar.  Returns
    ``None`` (never raises) when the step cannot be costed."""
    from torchgpipe_tpu.layers import sequential_apply

    flat_p = [p for stage in params for p in stage]
    flat_s = [s for stage in state for s in stage]

    def step(fp, xx, yy):
        def loss_of(fp):
            out, _ = sequential_apply(
                model.layers, fp, flat_s, xx, rng=rng, train=True
            )
            loss = loss_fn(out, yy)
            return loss[0] if isinstance(loss, tuple) else loss

        return jax.value_and_grad(loss_of)(fp)

    try:
        return analytic_flops(step, flat_p, x, y)
    except Exception:
        return None


def run_speed(
    model: GPipe,
    x,
    y,
    loss_fn: Callable,
    *,
    epochs: int = 3,
    steps_per_epoch: int = 10,
    skip_epochs: int = 1,
    label: str = "experiment",
    after: Optional[Callable] = None,
    reporter=None,
) -> float:
    """Timed SGD epochs through the GPipe engine; steady-state samples/sec.

    ``after(params, state)`` (optional) runs on the trained values once the
    loop finishes — e.g. the MoE driver prints router balance stats.  On a
    chip with a known bf16 peak an ``MFU`` line follows the epoch lines
    (:func:`print_mfu`).

    ``reporter`` is a :class:`torchgpipe_tpu.obs.StepReporter` (one is
    created by default): every driver step ticks it, and one structured
    ``OBS |`` summary line (step-time p50/p95, samples/s, first-step
    compile time) closes the run — the telemetry every speed benchmark
    reports against.  Dispatch-granularity times: the loop blocks per
    epoch, so per-step figures include async overlap (throughput truth
    lives in the epoch lines; the percentiles catch recompiles and
    stragglers).
    """
    in_spec = jax.ShapeDtypeStruct(x.shape, x.dtype)
    params, state = model.init(jax.random.PRNGKey(0), in_spec)
    rng = jax.random.PRNGKey(1)
    carry = {"params": params, "state": state}

    if reporter is None:
        from torchgpipe_tpu.obs import StepReporter

        reporter = StepReporter(
            items_per_step=x.shape[0], items_label="samples",
            label=label, log_every=0,
        )

    # The input pipeline the drivers measure WITH, not around: batches
    # stream through the double-buffered prefetcher (utils.data), so the
    # host→device copy of batch k+1 overlaps step k's compute — the
    # hot-path wiring docs/tuning.md's input-pipeline section describes.
    from itertools import repeat

    from torchgpipe_tpu.utils.data import prefetch_to_pipe

    batches = prefetch_to_pipe(repeat((x, y)), model, size=2)

    def step_fn(global_step):
        key = jax.random.fold_in(rng, global_step)
        xb, yb = next(batches)
        loss, grads, new_state, _ = model.value_and_grad(
            carry["params"], carry["state"], xb, yb, loss_fn, rng=key
        )
        carry["params"] = tuple(
            jax.tree_util.tree_map(lambda p, g: p - 1e-4 * g, ps, gs)
            for ps, gs in zip(carry["params"], grads)
        )
        carry["state"] = new_state
        reporter.step()
        return loss, carry["params"]

    tput = run_epoch_loop(
        step_fn, x.shape[0], epochs=epochs, steps_per_epoch=steps_per_epoch,
        skip_epochs=skip_epochs, label=label,
    )
    print(reporter.line(), flush=True)
    print_mfu(
        lambda: sequential_step_flops(
            model, params, state, x, y, loss_fn, rng
        ),
        tput, x.shape[0], label, n_chips=distinct_chips(model),
        device=model.devices[0],
    )
    if after is not None:
        after(carry["params"], carry["state"])
    return tput


def run_memory(
    model: GPipe, x, y, loss_fn: Callable, *, label: str = "experiment"
) -> Tuple[int, List[int]]:
    """Parameter count + per-device peak memory for one training step.

    The reference reads ``torch.cuda.max_memory_*`` per device
    (benchmarks/unet-memory/main.py RESULT section); TPU equivalent is
    ``device.memory_stats()['peak_bytes_in_use']`` where available (real TPU),
    falling back to live params bytes on host platforms.
    """
    in_spec = jax.ShapeDtypeStruct(x.shape, x.dtype)
    params, state = model.init(jax.random.PRNGKey(0), in_spec)
    n_params = sum(
        leaf.size for leaf in jax.tree_util.tree_leaves(params)
    )
    loss, grads, state, _ = model.value_and_grad(
        params, state, x, y, loss_fn, rng=jax.random.PRNGKey(1)
    )
    jax.block_until_ready((loss, grads))

    peaks: List[int] = []
    for dev in dict.fromkeys(model.devices):
        stats = getattr(dev, "memory_stats", lambda: None)()
        if stats and "peak_bytes_in_use" in stats:
            peaks.append(int(stats["peak_bytes_in_use"]))
        else:
            stage_bytes = 0
            for j, d in enumerate(model.devices):
                if d == dev:
                    stage_bytes += sum(
                        leaf.size * leaf.dtype.itemsize
                        for leaf in jax.tree_util.tree_leaves(params[j])
                    )
            peaks.append(stage_bytes)
    print(
        f"RESULT | {label} | parameters: {n_params / 1e6:.1f}M | "
        f"per-device peak bytes: {[f'{p / 2**20:.0f}MiB' for p in peaks]}",
        flush=True,
    )
    return n_params, peaks
