"""Shared benchmark-driver plumbing.

Mirrors the reference drivers' structure (timed epochs over synthetic data,
``HH:MM:SS | throughput`` progress lines — reference:
benchmarks/amoebanetd-speed/main.py:121-138, 235-265) on the TPU-native
engine: one :func:`run_speed` / :func:`run_memory` pair serves every model
family.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from torchgpipe_tpu.gpipe import GPipe
from torchgpipe_tpu.layers import Layer


def hr_time(seconds: float) -> str:
    m, s = divmod(int(seconds), 60)
    h, m = divmod(m, 60)
    return f"{h:02d}:{m:02d}:{s:02d}"


def even_balance(n_layers: int, n_stages: int) -> List[int]:
    base, rem = divmod(n_layers, n_stages)
    return [base + (1 if j >= n_stages - rem else 0) for j in range(n_stages)]


def softmax_xent(out, tgt):
    logits = out.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits.reshape(-1, logits.shape[-1]))
    return -jnp.mean(logp[jnp.arange(logp.shape[0]), tgt.reshape(-1)])


def mse(out, tgt):
    return jnp.mean((out.astype(jnp.float32) - tgt) ** 2)


def build_gpipe(
    layers: Sequence[Layer],
    balance: Optional[Sequence[int]],
    n_stages: int,
    chunks: int,
    checkpoint: str,
    devices=None,
    tracer=None,
    bf16: bool = False,
    deferred_batch_norm: bool = False,
) -> GPipe:
    if balance is None:
        balance = even_balance(len(layers), n_stages)
    return GPipe(
        list(layers), balance, chunks=chunks, checkpoint=checkpoint,
        devices=devices, tracer=tracer,
        compute_dtype=jnp.bfloat16 if bf16 else None,
        deferred_batch_norm=deferred_batch_norm,
    )


def bf16_option(fn):
    """Shared ``--bf16`` click option: bfloat16 compute with f32 masters
    (torchgpipe_tpu.precision; no reference counterpart — the reference
    trains float32 only)."""
    import click

    return click.option(
        "--bf16/--no-bf16", default=False,
        help="bfloat16 compute, float32 masters + norm statistics",
    )(fn)


def run_epoch_loop(
    step_fn: Callable,
    batch: int,
    *,
    epochs: int,
    steps_per_epoch: int,
    skip_epochs: int = 1,
    label: str = "experiment",
) -> float:
    """Timed training epochs over ``step_fn(global_step) -> (loss, block_on)``;
    returns steady-state samples/sec.

    Reference loop shape: benchmarks/amoebanetd-speed/main.py:235-265
    (first epoch discarded as warm-up/compile).  With a single epoch nothing
    can be discarded, so the warm-up epoch is measured rather than reporting
    zero.
    """
    skip = skip_epochs if epochs > skip_epochs else 0
    throughputs = []
    t_start = time.time()
    for epoch in range(epochs):
        t0 = time.time()
        for step in range(steps_per_epoch):
            loss, block_on = step_fn(epoch * steps_per_epoch + step)
        jax.block_until_ready(block_on)
        dt = time.time() - t0
        tput = batch * steps_per_epoch / dt
        if epoch >= skip:
            throughputs.append(tput)
        print(
            f"{hr_time(time.time() - t_start)} | {label} | epoch {epoch + 1}: "
            f"{tput:.1f} samples/sec, loss {float(loss):.4f}"
            + ("  (warm-up)" if epoch < skip else ""),
            flush=True,
        )
    return sum(throughputs) / max(1, len(throughputs))


def run_speed(
    model: GPipe,
    x,
    y,
    loss_fn: Callable,
    *,
    epochs: int = 3,
    steps_per_epoch: int = 10,
    skip_epochs: int = 1,
    label: str = "experiment",
    after: Optional[Callable] = None,
) -> float:
    """Timed SGD epochs through the GPipe engine; steady-state samples/sec.

    ``after(params, state)`` (optional) runs on the trained values once the
    loop finishes — e.g. the MoE driver prints router balance stats.
    """
    in_spec = jax.ShapeDtypeStruct(x.shape, x.dtype)
    params, state = model.init(jax.random.PRNGKey(0), in_spec)
    rng = jax.random.PRNGKey(1)
    carry = {"params": params, "state": state}

    def step_fn(global_step):
        key = jax.random.fold_in(rng, global_step)
        loss, grads, new_state, _ = model.value_and_grad(
            carry["params"], carry["state"], x, y, loss_fn, rng=key
        )
        carry["params"] = tuple(
            jax.tree_util.tree_map(lambda p, g: p - 1e-4 * g, ps, gs)
            for ps, gs in zip(carry["params"], grads)
        )
        carry["state"] = new_state
        return loss, carry["params"]

    tput = run_epoch_loop(
        step_fn, x.shape[0], epochs=epochs, steps_per_epoch=steps_per_epoch,
        skip_epochs=skip_epochs, label=label,
    )
    if after is not None:
        after(carry["params"], carry["state"])
    return tput


def run_memory(
    model: GPipe, x, y, loss_fn: Callable, *, label: str = "experiment"
) -> Tuple[int, List[int]]:
    """Parameter count + per-device peak memory for one training step.

    The reference reads ``torch.cuda.max_memory_*`` per device
    (benchmarks/unet-memory/main.py RESULT section); TPU equivalent is
    ``device.memory_stats()['peak_bytes_in_use']`` where available (real TPU),
    falling back to live params bytes on host platforms.
    """
    in_spec = jax.ShapeDtypeStruct(x.shape, x.dtype)
    params, state = model.init(jax.random.PRNGKey(0), in_spec)
    n_params = sum(
        leaf.size for leaf in jax.tree_util.tree_leaves(params)
    )
    loss, grads, state, _ = model.value_and_grad(
        params, state, x, y, loss_fn, rng=jax.random.PRNGKey(1)
    )
    jax.block_until_ready((loss, grads))

    peaks: List[int] = []
    for dev in dict.fromkeys(model.devices):
        stats = getattr(dev, "memory_stats", lambda: None)()
        if stats and "peak_bytes_in_use" in stats:
            peaks.append(int(stats["peak_bytes_in_use"]))
        else:
            stage_bytes = 0
            for j, d in enumerate(model.devices):
                if d == dev:
                    stage_bytes += sum(
                        leaf.size * leaf.dtype.itemsize
                        for leaf in jax.tree_util.tree_leaves(params[j])
                    )
            peaks.append(stage_bytes)
    print(
        f"RESULT | {label} | parameters: {n_params / 1e6:.1f}M | "
        f"per-device peak bytes: {[f'{p / 2**20:.0f}MiB' for p in peaks]}",
        flush=True,
    )
    return n_params, peaks
