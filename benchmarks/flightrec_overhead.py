"""Flight-recorder overhead rung: the always-on ring buffer on vs off.

The flight recorder's promise (docs/observability.md) is a black box
that is ALWAYS ON in multi-rank runs — which only holds if recording
costs nothing measurable.  This rung times a 2-rank LocalTransport
``DistributedGPipe`` training step (llama blocks, the trace_report
fixture's sizing so cells are ~1-4ms) twice: bare, and with a
:class:`~torchgpipe_tpu.obs.flightrec.FlightRecorder` per rank PLUS a
running :class:`~torchgpipe_tpu.obs.flightrec.StallWatchdog` — the full
always-on configuration, ~50 recorded events per step (send enqueues,
recv wait/match pairs with mailbox depth, per-cell completions, loop
boundaries, arrival events from the mailbox).

Protocol is ``--obs-overhead``'s A/B-interleaved family, hardened for
the noisier two-rank step: each round times one bare and one
instrumented step back-to-back (PAIRED, so host scheduling drift hits
both sides of a ratio equally), the per-round ratios are medianed, and
the gate is median ratio − 1 **< 2%** (``BENCH_NOTES.md`` records the
measured figure).  Emits one JSON line (the bench contract)::

    env JAX_PLATFORMS=cpu python bench.py --flightrec-overhead
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

OVERHEAD_GATE = 0.02  # <2% instrumented-over-bare, the documented bound
CHUNKS = 4
N_STAGES = 2
ROUNDS = 16  # per-arm measured steps (paired A/B per round)


def _build(with_recorder: bool) -> Tuple[Any, Any, Any, Any]:
    """One complete 2-rank in-process pipeline (both rank objects over a
    shared LocalTransport — the serialized single-process drive the
    schedule-verifier fixtures use), optionally instrumented."""
    import jax.numpy as jnp

    from torchgpipe_tpu.distributed import DistributedGPipe, LocalTransport
    from torchgpipe_tpu.models.transformer import TransformerConfig, llama
    from torchgpipe_tpu.obs.flightrec import FlightRecorder, StallWatchdog

    cfg = TransformerConfig(
        vocab=256, dim=128, n_layers=2 * N_STAGES, n_heads=4,
        n_kv_heads=2, mlp_ratio=2.0,
    )
    blocks = llama(cfg)[1:-1]  # uniform stack: no embed/head imbalance
    workers = [f"w{r}" for r in range(N_STAGES)]
    tag = "rec" if with_recorder else "bare"
    transport = LocalTransport()
    ranks: List[Any] = []
    recs: List[Any] = []
    watchdogs: List[Any] = []
    for r in range(N_STAGES):
        box = transport.register(f"{tag}-{workers[r]}")
        rec = (
            FlightRecorder(rank=r, worker=workers[r])
            if with_recorder else None
        )
        if rec is not None:
            recs.append(rec)
            # The full always-on configuration includes the liveness
            # alarm (a 30s watchdog never fires here; its polling is
            # part of the measured cost).
            watchdogs.append(StallWatchdog(rec, timeout=30.0).start())
        ranks.append(DistributedGPipe(
            blocks, r, [f"{tag}-{w}" for w in workers],
            [2] * N_STAGES, chunks=CHUNKS,
            transport=transport, mailbox=box, recorder=rec,
        ))
    x = jnp.zeros((8, 32, cfg.dim), jnp.float32)
    return ranks, x, recs, watchdogs


def _stepper(ranks: Any, x: Any) -> Callable[[int], float]:
    """Returns ``run(i) -> seconds`` for one blocked 2-rank training
    step driven serially in this process (rank 0 forward -> rank 1
    forward -> loss -> rank 1 backward -> rank 0 backward)."""
    import jax
    import jax.numpy as jnp

    def loss_fn(out: Any, tgt: Any) -> Any:
        return jnp.mean((out.astype(jnp.float32) - tgt) ** 2)

    in_spec = jax.ShapeDtypeStruct(x.shape, x.dtype)
    ps = [rk.init(jax.random.PRNGKey(0), in_spec) for rk in ranks]

    def run(i: int) -> float:
        t0 = time.perf_counter()
        ranks[0].forward(ps[0][0], ps[0][1], x)
        outs = ranks[1].forward(ps[1][0], ps[1][1], None)
        loss, gouts, _ = ranks[1].loss_grads(outs, x, loss_fn)
        g1, _ = ranks[1].backward(gouts)
        g0, _ = ranks[0].backward(None)
        jax.block_until_ready((loss, g0, g1))
        return time.perf_counter() - t0

    run(0)  # compile warmup, outside the timed rounds
    return run


def run() -> Dict[str, Any]:
    bare_ranks, x, _, _ = _build(with_recorder=False)
    inst_ranks, _, recs, watchdogs = _build(with_recorder=True)
    bare = _stepper(bare_ranks, x)
    inst = _stepper(inst_ranks, x)
    bare_times: List[float] = []
    inst_times: List[float] = []
    ratios: List[float] = []
    for i in range(1, ROUNDS + 1):
        tb = bare(i)
        to = inst(i)
        bare_times.append(tb)
        inst_times.append(to)
        # Paired ratio: the two steps ran back-to-back, so a host
        # scheduling spike inflates both sides instead of one arm.
        ratios.append(to / tb)
    for w in watchdogs:
        w.stop()
    bare_times.sort()
    inst_times.sort()
    ratios.sort()
    b = bare_times[len(bare_times) // 2]
    o = inst_times[len(inst_times) // 2]
    overhead = ratios[len(ratios) // 2] - 1.0
    events_per_step = sum(len(r.events()) for r in recs) // (ROUNDS + 1)
    assert all(r.events() for r in recs), (
        "instrumented arm recorded no flight events"
    )
    return {
        "metric": "flightrec overhead "
                  "[2-rank llama blocks, cpu, recorder+watchdog]",
        "value": round(overhead * 100, 3),
        "unit": "percent",
        "platform": "cpu",
        # Per-step blocking in both arms: neither can over-report.
        "validated": True,
        "gate_percent": OVERHEAD_GATE * 100,
        "pass": overhead < OVERHEAD_GATE,
        "bare_step_ms": round(b * 1e3, 3),
        "instrumented_step_ms": round(o * 1e3, 3),
        "events_per_step": events_per_step,
    }


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    result = run()
    print(json.dumps(result), flush=True)
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
