"""Sequential ResNet-101 speed benchmark.

Reference: benchmarks/resnet101-speed/main.py:21-77 — baseline (no pipeline)
plus pipeline-1/2/4/8 with hand-tuned batch/chunks/balance, fake data,
samples/sec.  Balances default to an even split (the reference's hand
balances are tuned to P40s; retune with ``torchgpipe_tpu.balance``).
"""

from __future__ import annotations

import click
import jax
import jax.numpy as jnp

from benchmarks.common import bf16_option, build_gpipe, run_speed, softmax_xent
from torchgpipe_tpu.models import resnet101

# name -> (n_stages, batch, chunks)
EXPERIMENTS = {
    "baseline": (1, 118, 1),
    "pipeline-1": (1, 220, 2),
    "pipeline-2": (2, 512, 16),
    "pipeline-4": (4, 1024, 64),
    "pipeline-8": (8, 2048, 64),
}


@click.command()
@click.argument("experiment", type=click.Choice(sorted(EXPERIMENTS)))
@click.option("--epochs", default=3)
@click.option("--steps", default=10)
@click.option("--image", default=224)
@click.option("--batch", default=None, type=int)
@click.option("--base-width", default=64)
@bf16_option
def main(experiment, epochs, steps, image, batch, base_width, bf16):
    n, bsz, chunks = EXPERIMENTS[experiment]
    bsz = batch or bsz
    layers = resnet101(num_classes=1000, base_width=base_width)
    model = build_gpipe(layers, None, n, chunks, "except_last", bf16=bf16)
    x = jnp.zeros((bsz, image, image, 3), jnp.float32)
    y = jax.random.randint(jax.random.PRNGKey(0), (bsz,), 0, 1000)
    tput = run_speed(
        model, x, y, softmax_xent,
        epochs=epochs, steps_per_epoch=steps, label=experiment,
    )
    print(f"FINAL | resnet101-speed {experiment}: {tput:.1f} samples/sec")


if __name__ == "__main__":
    main()
