"""Long-context training tour: the three sequence-scaling tools together.

One Llama-style pipeline, three ways to push the sequence axis (all new
TPU-native capability — the reference has no sequence parallelism or
attention kernels at all, SURVEY.md §2.2/§5):

1. **Ring attention** (``sp_impl='ring'``): the sequence is sharded over
   the ``sp`` mesh axis; K/V blocks rotate by ``ppermute`` while each
   device accumulates online-softmax attention — O(s/sp) attention memory
   per device, the extreme-length tool.
2. **Ulysses** (``sp_impl='ulysses'``): one ``all_to_all`` re-shards
   sequence→heads so each device runs plain full-sequence attention for
   h/sp heads (flash-kernel-eligible), and one swaps back — the
   moderate-length tool when head count divides the sp size.
3. **Sliding-window attention** (``attn_window=N``): attend iff
   ``0 <= qpos - kpos < N`` — compute scales with the window, not the
   sequence; composes with Ulysses (each lane windows its full-sequence
   local compute exactly).

CPU run (8 virtual devices):

    env PYTHONPATH=. JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context.py

On TPU hardware the same script uses the Pallas flash kernels
automatically (resident or streaming by K/V footprint, causal/band block
skipping either way).
"""

import jax
import jax.numpy as jnp

from torchgpipe_tpu.models.transformer import (
    TransformerConfig,
    cross_entropy,
    llama_spmd,
)
from torchgpipe_tpu.spmd import SpmdGPipe, make_mesh


def train_3_steps(tag: str, cfg: TransformerConfig, mesh, **engine_kw):
    block, pre, post = llama_spmd(cfg, cfg.n_layers)
    pipe = SpmdGPipe(
        block, cfg.n_layers, mesh, chunks=2, loss_fn=cross_entropy,
        pre=pre, post=post, **engine_kw,
    )
    tokens = jnp.arange(4 * 64, dtype=jnp.int32).reshape(4, 64) % cfg.vocab
    labels = (tokens + 1) % cfg.vocab
    params = pipe.init(
        jax.random.PRNGKey(0), jax.ShapeDtypeStruct(tokens.shape, tokens.dtype)
    )
    losses = []
    for step in range(3):
        loss, grads = pipe.train_step(
            params, tokens, labels, jax.random.PRNGKey(step)
        )
        params = jax.tree_util.tree_map(
            lambda p, g: p - 1e-2 * g, params, grads
        )
        losses.append(float(loss))
    print(f"{tag}: losses {[round(v, 3) for v in losses]}", flush=True)
    assert losses[-1] < losses[0]


def build_for_lint():
    """Static-analysis entrypoint (tools/pipeline_lint.py): one case per
    sequence-scaling tool, traced abstractly."""
    pp, sp = 2, 2
    mesh = make_mesh(pp, 1, sp, devices=jax.devices()[: pp * sp])
    base = dict(vocab=128, dim=64, n_layers=pp, n_heads=4, n_kv_heads=2)
    x = jax.ShapeDtypeStruct((4, 64), jnp.int32)
    cases = []
    for name, cfg in (
        ("ring", TransformerConfig(**base, sp_axis="sp", sp_impl="ring")),
        ("ulysses", TransformerConfig(**base, sp_axis="sp",
                                      sp_impl="ulysses")),
        ("ulysses-window", TransformerConfig(
            **base, sp_axis="sp", sp_impl="ulysses", attn_window=16)),
    ):
        block, pre, post = llama_spmd(cfg, cfg.n_layers)
        pipe = SpmdGPipe(
            block, cfg.n_layers, mesh, chunks=2, loss_fn=cross_entropy,
            pre=pre, post=post, sp_axis="sp",
        )
        cases.append({"name": name, "pipe": pipe, "x": x})
    return cases


def main() -> None:
    pp, sp = 2, 2
    mesh = make_mesh(pp, 1, sp, devices=jax.devices()[: pp * sp])
    base = dict(vocab=128, dim=64, n_layers=pp, n_heads=4, n_kv_heads=2)

    train_3_steps(
        "ring attention  (sp=2)",
        TransformerConfig(**base, sp_axis="sp", sp_impl="ring"),
        mesh, sp_axis="sp",
    )
    train_3_steps(
        "ulysses         (sp=2)",
        TransformerConfig(**base, sp_axis="sp", sp_impl="ulysses"),
        mesh, sp_axis="sp",
    )
    train_3_steps(
        "ulysses + window(16)  ",
        TransformerConfig(
            **base, sp_axis="sp", sp_impl="ulysses", attn_window=16
        ),
        mesh, sp_axis="sp",
    )
    print("long-context tour complete", flush=True)


if __name__ == "__main__":
    main()
