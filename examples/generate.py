"""Train with the pipeline, decode with the same weights.

A 60-second end-to-end tour of :mod:`torchgpipe_tpu.models.generation`:
a tiny llama learns "next token = previous + 1 (mod vocab)" through the
MPMD GPipe engine, then the KV-cache generator continues prompts from
the SAME per-stage params (``mpmd_params_for_generation`` — no weight
conversion) and we check it reproduces the learned sequence.

CPU (8 virtual devices):

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/generate.py

On TPU just run it.
"""

import jax
import jax.numpy as jnp

from torchgpipe_tpu import GPipe
from torchgpipe_tpu.models import generate, mpmd_params_for_generation
from torchgpipe_tpu.models.transformer import (
    TransformerConfig,
    cross_entropy,
    llama,
)


def build_model():
    cfg = TransformerConfig(
        vocab=32, dim=32, n_layers=2, n_heads=4, n_kv_heads=2
    )
    return cfg, GPipe(llama(cfg), balance=[2, 2], chunks=2)


def build_for_lint():
    """Static-analysis entrypoint (tools/pipeline_lint.py)."""
    _, model = build_model()
    x = jax.ShapeDtypeStruct((4, 12), jnp.int32)
    return model, x, x, cross_entropy


def main() -> None:
    cfg, model = build_model()
    b, s = 4, 12
    data = jnp.mod(jnp.arange(s + 1)[None, :] + jnp.arange(b)[:, None], 32)
    x, y = data[:, :-1], data[:, 1:]
    params, state = model.init(
        jax.random.PRNGKey(0), jax.ShapeDtypeStruct(x.shape, x.dtype)
    )
    for step in range(60):
        loss, grads, state, _ = model.value_and_grad(
            params, state, x, y, cross_entropy
        )
        params = tuple(
            jax.tree_util.tree_map(lambda p, g: p - 0.5 * g, ps, gs)
            for ps, gs in zip(params, grads)
        )
        if step % 20 == 0:
            print(f"[generate] step {step} loss {float(loss):.4f}", flush=True)

    flat = mpmd_params_for_generation(model, params)
    prompt = data[:, :6]
    out = generate(cfg, flat, prompt, max_new_tokens=5)
    expect = jnp.mod(prompt[:, -1:] + jnp.arange(1, 6)[None, :], 32)
    acc = float(jnp.mean((out == expect).astype(jnp.float32)))
    print(f"[generate] greedy {out[0].tolist()} "
          f"(expected {expect[0].tolist()}), accuracy {acc:.2f}")
    assert acc > 0.9, acc

    # Beam search scores the same completion (deterministic data).
    from torchgpipe_tpu.models import beam_search

    beams, lp = beam_search(cfg, flat, prompt, 5, num_beams=3)
    print(f"[generate] beam-3 {beams[0].tolist()} "
          f"(log-prob {float(lp[0]):.3f})")
    assert (beams == out).all()

    # Multi-turn continuation: keep the cache, feed the next chunk.
    out1, state = generate(
        cfg, flat, prompt, max_new_tokens=3, return_state=True, max_len=24
    )
    out2 = generate(cfg, flat, out1[:, -1:] * 0 + expect[:, 3:4],
                    max_new_tokens=3, cache=state)
    print(f"[generate] turn-2 continuation {out2[0].tolist()}")

    # Speculative decoding: a half-size draft trained on the same data
    # proposes 3 tokens/round; the target verifies each round in ONE
    # chunked forward.  Both models learned the sequence, so acceptance
    # is high — and the output must equal plain greedy decode exactly.
    from torchgpipe_tpu.layers import sequential_init
    from torchgpipe_tpu.models import speculative_generate

    dcfg = TransformerConfig(vocab=32, dim=16, n_layers=1, n_heads=2,
                             n_kv_heads=1)
    dlayers = llama(dcfg)
    dparams, dstate, _ = sequential_init(
        dlayers, jax.random.PRNGKey(1),
        jax.ShapeDtypeStruct(x.shape, x.dtype),
    )
    from torchgpipe_tpu.layers import sequential_apply

    def dloss(p, s_, x_, y_):
        out_, _ = sequential_apply(dlayers, p, s_, x_, rng=None, train=True)
        return cross_entropy(out_, y_)

    dgrad = jax.jit(jax.grad(dloss))
    for _ in range(60):
        dparams = jax.tree_util.tree_map(
            lambda p, g: p - 0.5 * g, dparams, dgrad(dparams, dstate, x, y)
        )
    spec, stats = speculative_generate(
        cfg, flat, dcfg, dparams, prompt, 5, gamma=3, return_stats=True
    )
    assert (spec == out).all()
    acc_rate = float(stats.accepted.sum()) / float(stats.drafted.sum())
    print(f"[generate] speculative == greedy, draft acceptance "
          f"{acc_rate:.0%}, {int(stats.rounds.sum())} target passes for "
          f"{spec.size} tokens")
    print("generate demo complete")


if __name__ == "__main__":
    main()
