"""Multi-host SPMD pipeline training, runnable WITHOUT a pod.

Launches itself twice (two OS processes, 4 virtual CPU devices each) and
joins them into ONE global 8-device mesh via ``jax.distributed`` — the
same topology as two TPU hosts over DCN.  Each process then:

* builds a dp-outermost ``(dp, pp)`` mesh so it owns a whole data slice,
* feeds ONLY its own rows of the global batch
  (``utils.data.global_batch_from_local`` — no host holds the full batch),
* runs the compiled pipelined training step — the ``pp`` ppermute
  hand-offs and the ``dp`` gradient pmean cross the process boundary,
* checkpoints with ``save_sharded`` (rank-0-gated atomic swap).

On a real pod: drop the self-launch, call ``jax.distributed.initialize()``
(TPU auto-detection) on every host, and keep everything else identical.
See docs/multihost.md for the full recipe.

Run: ``python examples/multihost_llama.py``
"""

import os
import subprocess
import sys

PORT = os.environ.get("MULTIHOST_EXAMPLE_PORT", "29471")


def launch_both() -> None:
    import time

    procs = []
    codes = []
    deadline = time.monotonic() + 540  # overall, not per rank
    try:
        for rank in range(2):
            env = dict(os.environ, MULTIHOST_EXAMPLE_RANK=str(rank))
            procs.append(
                subprocess.Popen([sys.executable, __file__], env=env)
            )
        for p in procs:
            codes.append(p.wait(timeout=max(1, deadline - time.monotonic())))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(codes):
        raise SystemExit(f"rank exit codes: {codes}")
    print("multihost example: both ranks OK")


def build_for_lint():
    """Static-analysis entrypoint (tools/pipeline_lint.py): the same
    (dp, pp) topology run_rank() builds across two processes, constructed
    on this process's 8 virtual devices — the linter only needs the traced
    program, which is identical either way."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from torchgpipe_tpu.models.transformer import (
        TransformerConfig,
        cross_entropy,
        llama_spmd,
    )
    from torchgpipe_tpu.spmd import SpmdGPipe

    pp, dp, m = 4, 2, 4
    cfg = TransformerConfig(
        vocab=256, dim=64, n_layers=pp, n_heads=4, n_kv_heads=2
    )
    block, pre, post = llama_spmd(cfg, pp)
    mesh = Mesh(np.array(jax.devices()[: dp * pp]).reshape(dp, pp),
                ("dp", "pp"))
    pipe = SpmdGPipe(
        block, pp, mesh, chunks=m, loss_fn=cross_entropy,
        pre=pre, post=post, dp_axis="dp",
    )
    x = jax.ShapeDtypeStruct((m * dp * 2, 16), jnp.int32)
    return pipe, x


def run_rank(rank: int) -> None:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{PORT}",
        num_processes=2,
        process_id=rank,
    )

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from torchgpipe_tpu.models.transformer import (
        TransformerConfig,
        cross_entropy,
        llama_spmd,
    )
    from torchgpipe_tpu.spmd import SpmdGPipe
    from torchgpipe_tpu.utils.data import global_batch_from_local

    pp, dp, m = 4, 2, 4
    cfg = TransformerConfig(
        vocab=256, dim=64, n_layers=pp, n_heads=4, n_kv_heads=2
    )
    block, pre, post = llama_spmd(cfg, pp)
    # dp OUTERMOST: process r owns dp slice r, so it feeds only its rows.
    mesh = Mesh(np.array(jax.devices()).reshape(dp, pp), ("dp", "pp"))
    pipe = SpmdGPipe(
        block, pp, mesh, chunks=m, loss_fn=cross_entropy,
        pre=pre, post=post, dp_axis="dp",
    )

    B = m * dp * 2  # global batch
    params = pipe.init(
        jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((B, 16), jnp.int32),
    )

    rows0 = rank * (B // 2)  # this process's first global row
    n_rows = B // 2
    for step in range(5):
        # Each process materializes ONLY its own rows of the (virtual)
        # global batch — the arange is offset by the global row index, so
        # no host ever holds the full [B, 16] array.
        local = (
            np.arange(rows0 * 16, (rows0 + n_rows) * 16, dtype=np.int32)
            .reshape(n_rows, 16)
            + step
        ) % 256
        tokens = global_batch_from_local(mesh, P("dp"), local)
        labels = global_batch_from_local(mesh, P("dp"), (local + 1) % 256)
        loss, grads = pipe.train_step(params, tokens, labels)
        params = jax.tree_util.tree_map(
            lambda p, g: p - 0.1 * g, params, grads
        )
        if rank == 0:
            print(f"step {step}: loss {float(loss):.4f}", flush=True)

    # Sharded checkpoint: every process calls save_sharded; the atomic
    # directory swap is process-0-gated (utils/serialization.py).
    try:
        from torchgpipe_tpu.utils.serialization import save_sharded

        # Per-run path (keyed by the coordinator port) so concurrent
        # runs cannot race inside save_sharded's atomic swap.
        path = os.path.join(
            os.environ.get("TMPDIR", "/tmp"), f"multihost_llama_ckpt_{PORT}"
        )
        save_sharded(path, params)
        if rank == 0:
            print(f"checkpoint saved to {path}", flush=True)
    except ModuleNotFoundError:
        pass  # orbax not installed — checkpointing is optional here


if __name__ == "__main__":
    r = os.environ.get("MULTIHOST_EXAMPLE_RANK")
    if r is None:
        launch_both()
    else:
        run_rank(int(r))
