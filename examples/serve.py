"""Train with the pipeline, SERVE with the same weights.

The serving tour of :mod:`torchgpipe_tpu.serving`: the tiny llama from
``examples/generate.py`` learns "next token = previous + 1 (mod vocab)"
through the MPMD GPipe engine, then a continuous-batching
:class:`~torchgpipe_tpu.serving.Engine` (slot-pooled KV cache, chunked
prefill interleaved with decode, per-row eviction) serves a burst of
staggered, ragged-length requests from the SAME per-stage params
(``mpmd_params_for_generation`` — no weight conversion), streaming
tokens as they land.  The engine stays at exactly TWO compiled programs
through all the churn, and every pooled output matches the learned
sequence.

CPU (8 virtual devices):

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/serve.py

On TPU just run it.
"""

import jax
import jax.numpy as jnp
import numpy as np

from torchgpipe_tpu import GPipe
from torchgpipe_tpu.models import mpmd_params_for_generation
from torchgpipe_tpu.models.transformer import (
    TransformerConfig,
    cross_entropy,
    llama,
)
from torchgpipe_tpu.serving import Engine

VOCAB = 32


def build_model():
    cfg = TransformerConfig(
        vocab=VOCAB, dim=32, n_layers=2, n_heads=4, n_kv_heads=2
    )
    return cfg, GPipe(llama(cfg), balance=[2, 2], chunks=2)


def build_for_lint():
    """Static-analysis entrypoint (tools/pipeline_lint.py)."""
    _, model = build_model()
    x = jax.ShapeDtypeStruct((4, 12), jnp.int32)
    return model, x, x, cross_entropy


def main() -> None:
    cfg, model = build_model()
    b, s = 8, 12
    # Rows start every 4 tokens, so the batch covers every v -> v+1
    # transition of the mod-32 ring — requests can then start anywhere.
    data = jnp.mod(
        jnp.arange(s + 1)[None, :] + (4 * jnp.arange(b))[:, None], VOCAB
    )
    x, y = data[:, :-1], data[:, 1:]
    params, state = model.init(
        jax.random.PRNGKey(0), jax.ShapeDtypeStruct(x.shape, x.dtype)
    )
    for step in range(60):
        loss, grads, state, _ = model.value_and_grad(
            params, state, x, y, cross_entropy
        )
        params = tuple(
            jax.tree_util.tree_map(lambda p, g: p - 0.5 * g, ps, gs)
            for ps, gs in zip(params, grads)
        )
        if step % 20 == 0:
            print(f"[serve] train step {step} loss {float(loss):.4f}",
                  flush=True)

    flat = mpmd_params_for_generation(model, params)

    # A burst of ragged requests with staggered arrivals: each prompt is
    # a window of the learned sequence, so every completion is known.
    rng = np.random.RandomState(0)
    bursts = []
    for i in range(10):
        start = int(rng.randint(0, VOCAB))
        plen = int(rng.randint(2, 7))
        new = int(rng.randint(2, 8))
        prompt = np.mod(start + np.arange(plen), VOCAB).astype(np.int32)
        expect = np.mod(prompt[-1] + 1 + np.arange(new), VOCAB)
        bursts.append((prompt, new, expect))

    streamed: dict = {}
    eng = Engine(cfg, flat, num_slots=4, max_len=16, prefill_chunk=4)
    rids = []
    for prompt, new, _ in bursts:
        rids.append(eng.submit(
            prompt, new,
            on_token=lambda rid, t: streamed.setdefault(rid, []).append(t),
        ))
        eng.step()   # staggered: the engine keeps serving between arrivals
    eng.run()

    hits = total = 0
    for rid, (prompt, new, expect) in zip(rids, bursts):
        out = eng.result(rid)
        assert streamed[rid] == out.tolist()   # streaming == final result
        hits += int((out == expect).sum())
        total += new
    acc = hits / total
    snap = eng.metrics.snapshot()
    print(f"[serve] {len(bursts)} ragged requests -> accuracy {acc:.2f}, "
          f"{snap['engine_steps']} engine steps "
          f"({snap['prefill_steps']} prefill / {snap['decode_steps']} "
          f"decode), occupancy {snap['occupancy']:.0%}, "
          f"{snap['tokens_per_step']:.2f} tokens/step")
    print(f"[serve] compile stats {eng.compile_stats} "
          "(two programs, zero retraces)")
    assert acc > 0.9, acc
    assert eng.compile_stats == {"prefill": 1, "decode": 1}, eng.compile_stats
    print("serve demo complete")


if __name__ == "__main__":
    main()
