"""Fine-tune a HuggingFace checkpoint with the SPMD pipeline, end to end.

The complete switch-to-this-framework loop in one file:

1. load a (tiny, random-init — no network in CI) HF Llama-family model;
2. import it with :mod:`torchgpipe_tpu.models.hf_interop` — tied
   checkpoints become the native tie, windows/biases/qk-norms map onto
   config knobs;
3. pipeline-train it with ``SpmdGPipe.make_train_step`` (the whole
   update — pipelined fwd+bwd plus the optax optimizer — as ONE
   compiled program over a pp x dp mesh), run PRODUCTION-SHAPED: the
   step is wrapped in a ``resilience.StepGuard`` (NaN steps skipped,
   transient errors retried), every step lands in an atomic versioned
   checkpoint, a ``PreemptionHandler`` turns SIGTERM into
   checkpoint-and-exit — and the run RESUMES from ``restore_latest()``
   (demonstrated in-process with a fault-injected preemption);
4. decode from the trained weights with the KV-cache generator;
5. export the result back to an HF state dict.

Run on the CPU mesh::

    env PYTHONPATH=. JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/hf_finetune.py
"""

from __future__ import annotations

import shutil
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import optax

PP, DP = 2, 2


def build_for_lint():
    """Static-analysis entrypoint (tools/pipeline_lint.py): the same
    HF-imported pipeline main() trains, built but not run — the linter
    traces it abstractly (tied head, pp x dp mesh, except_last remat)."""
    import torch
    import transformers

    from torchgpipe_tpu.models.hf_interop import from_hf_llama
    from torchgpipe_tpu.models.transformer import cross_entropy, llama_spmd
    from torchgpipe_tpu.spmd import SpmdGPipe, make_mesh

    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=256,
        num_hidden_layers=PP, num_attention_heads=4, num_key_value_heads=2,
        tie_word_embeddings=True,
    )
    torch.manual_seed(0)
    cfg, _ = from_hf_llama(transformers.LlamaForCausalLM(hf_cfg).eval())
    block, pre, post = llama_spmd(cfg, PP)
    mesh = make_mesh(PP, DP, devices=jax.devices()[: PP * DP])
    pipe = SpmdGPipe(
        block, PP, mesh, chunks=2, loss_fn=cross_entropy,
        pre=pre, post=post, dp_axis="dp", checkpoint="except_last",
    )
    x = jax.ShapeDtypeStruct((8, 15), jnp.int32)
    return pipe, x


def main() -> None:
    import torch
    import transformers

    from torchgpipe_tpu.models.generation import (
        generate,
        spmd_params_for_generation,
        spmd_params_from_flat,
    )
    from torchgpipe_tpu.models.hf_interop import (
        from_hf_llama,
        state_dict_to_hf,
    )
    from torchgpipe_tpu.models.transformer import cross_entropy, llama_spmd
    from torchgpipe_tpu.spmd import SpmdGPipe, make_mesh

    # 1. A tiny tied Llama (3.2-style) — stands in for a downloaded
    # checkpoint; real use: LlamaForCausalLM.from_pretrained(...).
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=256,
        num_hidden_layers=PP, num_attention_heads=4, num_key_value_heads=2,
        tie_word_embeddings=True,
    )
    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()

    # 2. Import: the tie arrives as the framework's native tie_embeddings.
    cfg, flat = from_hf_llama(hf_model)
    print(f"imported: tie={cfg.tie_embeddings}, {cfg.n_layers} blocks")

    # 3. Pipeline-train on a pp x dp mesh with the fused optimizer step.
    block, pre, post = llama_spmd(cfg, PP)
    mesh = make_mesh(PP, DP, devices=jax.devices()[: PP * DP])
    pipe = SpmdGPipe(
        block, PP, mesh, chunks=2, loss_fn=cross_entropy,
        pre=pre, post=post, dp_axis="dp", checkpoint="except_last",
    )
    params = spmd_params_from_flat(pipe, flat)
    opt = optax.adamw(3e-3)
    # donate=False: the StepGuard's skip-step hands back the pre-step
    # params after a non-finite update, so they must survive the call.
    step = pipe.make_train_step(opt, donate=False)
    opt_state = pipe.place_tree(opt.init(params))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab
    )
    # Causal-LM objective: the loss sees pre-shifted arrays (logits for
    # positions 0..s-2 against the NEXT token at 1..s-1).
    inputs, labels = tokens[:, :-1], tokens[:, 1:]

    # Production-shaped loop (docs/robustness.md): guarded step, atomic
    # versioned checkpoints, cooperative preemption.  A fault-injected
    # SIGTERM at step 3 stands in for the preemptible-VM notice; the
    # second loop below is "the next incarnation of the job".
    from torchgpipe_tpu.resilience import (
        CheckpointManager, PreemptionHandler, StepGuard, faults,
    )

    guard = StepGuard(step)
    ckpt_dir = tempfile.mkdtemp(prefix="hf_finetune_ckpt_")
    mgr = CheckpointManager(ckpt_dir, keep_last_k=2)

    # Telemetry (docs/observability.md): a StepReporter on the obs
    # registry ticks once per guarded step — step-time percentiles,
    # tokens/s, and the guard's skip/retry counters land in ONE
    # structured log line per step (log_every=1 because this example
    # runs 6 steps; production loops use 50-500).
    from torchgpipe_tpu.obs import StepReporter

    reporter = StepReporter(
        guard=guard, items_per_step=float(inputs.size),
        items_label="tokens", label="hf_finetune", log_every=1,
    )

    def pack(params, opt_state, i):
        return {"params": params, "opt": opt_state,
                "step": jnp.asarray(i, jnp.int32)}

    # The input pipeline: batches stream through the double-buffered
    # sharding-aware prefetcher (utils.data.prefetch_to_pipe) — batch
    # k+1's host→device copy, committed to the pp x dp mesh's data
    # sharding, overlaps step k's compute.  The loader is deterministic
    # per step index so a resumed incarnation replays the same stream.
    from torchgpipe_tpu.utils.data import prefetch_to_pipe

    def loader(start):
        step_i = start
        while True:
            yield inputs, labels  # a real loader would key on step_i
            step_i += 1

    total = 6
    batches = prefetch_to_pipe(loader(0), pipe, size=2)
    with PreemptionHandler() as stop:
        with faults.inject(preempt_at_step=3):
            for i in range(total):
                x_i, y_i = next(batches)
                loss, params, opt_state = guard(
                    params, opt_state, x_i, y_i
                )
                mgr.save(i, pack(params, opt_state, i))
                reporter.step(loss=float(loss))
                print(f"step {i}: loss {float(loss):.4f}", flush=True)
                if stop.check(i):
                    print(f"preempted at step {i}: checkpointed, exiting",
                          flush=True)
                    break

    # Resume: restore_latest() skips any corrupt/partial snapshot and
    # hands back the exact (params, opt_state, step) the dead run saved.
    snap = mgr.restore_latest(template=pack(params, opt_state, 0))
    params = pipe.place_tree(snap.tree["params"])
    opt_state = pipe.place_tree(snap.tree["opt"])
    start = int(snap.tree["step"]) + 1
    batches = prefetch_to_pipe(loader(start), pipe, size=2)
    for i, (x_i, y_i) in zip(range(start, total), batches):
        loss, params, opt_state = guard(params, opt_state, x_i, y_i)
        mgr.save(i, pack(params, opt_state, i))
        reporter.step(loss=float(loss))
        print(f"step {i} (resumed): loss {float(loss):.4f}", flush=True)
    print(f"guard stats: {guard.stats}", flush=True)
    print(reporter.line(), flush=True)
    shutil.rmtree(ckpt_dir, ignore_errors=True)

    # 4. Decode from the trained weights (single-host, KV-cache scan).
    unstacked = spmd_params_for_generation(pipe, params)
    out = generate(cfg, unstacked, tokens[:2, :6], max_new_tokens=4)
    print("decoded:", np.asarray(out))

    # 5. Export back to the HF ecosystem (tied layout preserved).
    sd = state_dict_to_hf(list(unstacked), cfg)
    assert "lm_head.weight" not in sd  # tied layout, like the source
    hf_model.load_state_dict(sd, strict=False)
    hf_model.tie_weights()
    print(f"exported {len(sd)} tensors back into the HF model")


if __name__ == "__main__":
    main()
