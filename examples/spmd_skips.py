"""U-Net-style skip connections on the flagship SPMD engine.

The SPMD engine compiles the whole pipeline into ONE scan+ppermute program,
so it cannot route a stashed activation from stage 2 to stage 5 the way the
MPMD engine (or the reference's portals,
reference: torchgpipe/skip/portal.py:1-8) does: there is no per-cell
dispatch to hang point-to-point routing on.  Its error message therefore
promises a workaround — "Resolve the skips inside a chain() stage"
(torchgpipe_tpu/spmd.py __post_init__) — and THIS file is that workaround,
runnable:

* each pipeline stage is a ``chain()`` holding a mini U-block: encoder
  ``dense`` → ``stash`` → narrower bottleneck → decoder ``dense`` →
  ``pop_cat`` (channel concat, the U-Net long-skip shape) → projection;
* the stash/pop pair RESOLVES WITHIN the chain, so the composed stage is
  skip-free at the engine boundary and every schedule / checkpoint mode /
  mesh axis composes as usual;
* a model whose long skips genuinely CROSS stage boundaries (the classic
  whole-model U-Net, models/unet.py) stays on the MPMD engine — that is
  the documented division of labor, not a gap: XLA keeps the stashed
  value alive inside the compiled stage exactly like a portal would,
  minus the copy machinery.

CPU (8 virtual devices):

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/spmd_skips.py
"""

import jax
import jax.numpy as jnp

from torchgpipe_tpu.layers import chain
from torchgpipe_tpu.ops import dense, gelu, layer_norm
from torchgpipe_tpu.skip import Namespace, pop_cat, stash
from torchgpipe_tpu.spmd import SpmdGPipe, make_mesh

DIM = 64


def u_stage(dim: int = DIM):
    """One pipeline stage = one mini-U: the long skip jumps the bottleneck
    and concatenates channels, resolved entirely inside the chain."""
    ns = Namespace()
    return chain(
        [
            layer_norm(name="ln"),
            dense(dim, name="enc"),
            stash("feat", ns=ns),            # ---- long skip starts here
            dense(dim // 4, name="down"),    # narrow bottleneck
            gelu("mid"),
            dense(dim, name="up"),
            pop_cat("feat", ns=ns),          # ---- lands here: [b, 2*dim]
            dense(dim, name="proj"),
        ],
        name="u_stage",
    )


def mse(out, tgt):
    return jnp.mean((out - tgt) ** 2)


def build_pipe(n_stages: int = 4, chunks: int = 4) -> SpmdGPipe:
    mesh = make_mesh(n_stages, 1, devices=jax.devices()[:n_stages])
    return SpmdGPipe(
        u_stage(), n_stages, mesh, chunks=chunks, loss_fn=mse,
        checkpoint="except_last",
    )


def build_for_lint():
    """Static-analysis entrypoint (tools/pipeline_lint.py): the in-stage
    skip resolution must survive the linter's structural rules too."""
    x = jax.ShapeDtypeStruct((8 * 4, DIM), jnp.float32)
    return build_pipe(), x


def main() -> None:
    n_stages, chunks = 4, 4
    pipe = build_pipe(n_stages, chunks)
    x = jax.random.normal(jax.random.PRNGKey(0), (8 * chunks, DIM))
    tgt = jnp.tanh(x[:, ::-1] * 0.5)
    params = pipe.place(
        pipe.init(jax.random.PRNGKey(1), jax.ShapeDtypeStruct(x.shape, x.dtype))
    )
    for step in range(6):
        loss, grads = pipe.train_step(params, x, tgt)
        params = jax.tree_util.tree_map(lambda a, g: a - 0.02 * g, params, grads)
        print(f"[spmd-skips] step {step} loss {float(loss):.5f}", flush=True)

    # Oracle: the same stacked params applied sequentially on one device —
    # the pipelined skip resolution must be transparent.
    def loss_of(blocks):
        h = x
        block = u_stage()
        for j in range(n_stages):
            pj = jax.tree_util.tree_map(lambda a: a[j], blocks)
            h, _ = block.apply(pj, (), h, rng=None, train=True)
        return mse(h, tgt)

    ref = float(loss_of(params["blocks"]))
    got = float(pipe.eval_loss(params, x, tgt))
    assert abs(got - ref) < 1e-4, (got, ref)
    print(f"[spmd-skips] pipelined == sequential oracle ({got:.5f})")
    print("spmd-skips demo complete")


if __name__ == "__main__":
    main()
