"""Runnable 60-second tour: both engines, training + inference.

CPU (8 virtual devices):

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/quickstart.py

On TPU just run it — the same code pipelines across the chips present.

Lint the pipelines without running them (tools/pipeline_lint.py imports
:func:`build_for_lint` below):

    python tools/pipeline_lint.py examples/quickstart.py
"""

import jax
import jax.numpy as jnp

# ----------------------------------------------------------------------- #
# 1. MPMD engine: any sequential model, any balance, any devices.         #
# ----------------------------------------------------------------------- #
from torchgpipe_tpu import GPipe
from torchgpipe_tpu.layers import named
from torchgpipe_tpu.ops import dense, gelu

PP, DP = 2, 2


def mse(out, tgt):
    return jnp.mean((out - tgt) ** 2)


def build_mpmd():
    """The MPMD pipeline: 2 stages, 4 micro-batches."""
    layers = named([
        dense(64, name="fc1"), gelu("a1"),
        dense(64, name="fc2"), gelu("a2"),
        dense(8, name="head"),
    ])
    return GPipe(layers, balance=[3, 2], chunks=4)


def run_mpmd():
    model = build_mpmd()
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 64))
    y = jax.random.normal(jax.random.PRNGKey(1), (16, 8))
    params, state = model.init(
        jax.random.PRNGKey(2), jax.ShapeDtypeStruct(x.shape, x.dtype)
    )
    for step in range(5):
        loss, grads, state, _ = model.value_and_grad(params, state, x, y, mse)
        params = tuple(
            jax.tree_util.tree_map(lambda p, g: p - 0.05 * g, ps, gs)
            for ps, gs in zip(params, grads)
        )
        print(f"[mpmd] step {step}: loss {float(loss):.4f}", flush=True)
    out, _ = model.apply(params, state, x)
    print("[mpmd] inference:", out.shape, flush=True)


# ----------------------------------------------------------------------- #
# 2. SPMD engine: a Llama-style pipeline compiled as ONE program on a     #
#    pp x dp mesh, with ZeRO-3 parameter sharding over dp.                #
# ----------------------------------------------------------------------- #
from torchgpipe_tpu import SpmdGPipe, make_mesh
from torchgpipe_tpu.models.transformer import (
    TransformerConfig, cross_entropy, llama_spmd,
)


def build_spmd():
    """The SPMD pipeline: Llama-style blocks on a pp x dp mesh + FSDP."""
    cfg = TransformerConfig(vocab=256, dim=64, n_layers=PP, n_heads=4,
                            n_kv_heads=2)
    block, pre, post = llama_spmd(cfg, PP)
    mesh = make_mesh(PP, DP)
    pipe = SpmdGPipe(block, PP, mesh, chunks=2, loss_fn=cross_entropy,
                     pre=pre, post=post, checkpoint="except_last",
                     dp_axis="dp", fsdp=True)
    return cfg, pipe


def run_spmd():
    cfg, pipe = build_spmd()
    tokens = jax.random.randint(jax.random.PRNGKey(3), (8, 32), 0, cfg.vocab)
    labels = jnp.roll(tokens, -1, axis=1)
    p = pipe.init(
        jax.random.PRNGKey(4), jax.ShapeDtypeStruct(tokens.shape, tokens.dtype)
    )
    for step in range(3):
        loss, grads = pipe.train_step(p, tokens, labels)
        p = jax.tree_util.tree_map(lambda a, g: a - 0.1 * g, p, grads)
        print(f"[spmd] step {step}: loss {float(loss):.4f}", flush=True)

    # Production shape: the whole update (pipeline + optimizer) as ONE
    # compiled program with donated buffers — no 2x params+moments HBM.
    import optax

    opt = optax.adamw(1e-2)
    fused = pipe.make_train_step(opt)
    opt_state = pipe.place_tree(opt.init(p))
    for step in range(3, 6):
        loss, p, opt_state = fused(p, opt_state, tokens, labels)
        print(f"[spmd/fused-opt] step {step}: loss {float(loss):.4f}",
              flush=True)


def build_for_lint():
    """Static-analysis entrypoint (tools/pipeline_lint.py): both engines,
    traced abstractly — shapes only, no training."""
    x = jax.ShapeDtypeStruct((16, 64), jnp.float32)
    y = jax.ShapeDtypeStruct((16, 8), jnp.float32)
    cases = [{"name": "mpmd", "pipe": build_mpmd(), "x": x,
              "target": y, "loss_fn": mse}]
    if len(jax.devices()) >= PP * DP:
        cfg, pipe = build_spmd()
        tokens = jax.ShapeDtypeStruct((8, 32), jnp.int32)
        cases.append({"name": "spmd", "pipe": pipe, "x": tokens})
    return cases


def main():
    run_mpmd()
    if len(jax.devices()) >= PP * DP:
        run_spmd()
    else:
        print(
            f"[spmd] skipped: needs {PP * DP} devices, "
            f"have {len(jax.devices())}"
        )
    print("quickstart done", flush=True)


if __name__ == "__main__":
    main()
