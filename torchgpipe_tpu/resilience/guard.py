"""Guarded training steps: skip bad updates, retry transient failures.

A multi-week pipeline run dies three ways that a correct *model* cannot
prevent: a non-finite loss/gradient poisons the optimizer state forever, a
transient infrastructure error (XLA ``RESOURCE_EXHAUSTED`` from a
fragmented allocator, a dropped transport send) kills the process even
though the very next attempt would succeed, and a genuine model bug gets
retried into oblivion instead of surfacing.  :class:`StepGuard` wraps a
step function with exactly those three policies:

* **Non-finite guard** — after each step, device-side ``isfinite``
  reductions over the loss and the updated params collapse to boolean
  scalars fetched in ONE host sync (lint-clean under the
  ``host-sync-in-loop`` rule: the reductions are their own tiny
  programs, not callbacks inside the pipelined loop).  A bad step is *skipped*:
  the caller gets back the params/opt-state it passed in, and the
  optional :class:`~torchgpipe_tpu.precision.DynamicLossScale` backs off
  (the mixed-precision overflow protocol).
* **Transient retry** — exceptions classified transient by
  :func:`classify_error` (XLA ``RESOURCE_EXHAUSTED``/``DATA_LOSS``,
  ``ConnectionError``, ``TimeoutError``) are retried under bounded
  exponential backoff.  Everything else — shape errors, user exceptions
  from a layer (the :mod:`tests.test_failures` semantics), a
  :class:`~torchgpipe_tpu.distributed.context.PeerDiedError` whose
  pipeline state cannot be retried in-process — re-raises immediately.

Contract: the wrapped step has the engines' ``make_train_step`` shape —
``step(params, opt_state, *data, **kw) -> (loss, new_params,
new_opt_state, *extras)``.  **Both policies require non-donated
buffers**: build the step with ``donate=False`` (both engines'
``make_train_step`` take it) — skip-step must return the params the
step would have consumed, and a retry must re-feed inputs the failed
attempt would have donated (the guard detects consumed buffers and
refuses the retry didactically rather than crash on deleted arrays).

**Megastep steps** (``make_train_step(megastep=K)``, detected via the
step's ``megastep`` attribute) move the finite check INSIDE the
compiled scan: the engine gates each inner step's update on a traced
all-finite reduction and reports the per-step mask as the step's last
output, so skip-step works even under ``donate=True`` (the returned
params are already protected — nothing needs restoring).  The guard
then only folds the mask into its statistics and backs the loss scale
off at MEGASTEP granularity; transient RETRY still needs
``donate=False``, and retries re-run the whole K-step program — the
documented granularity change of compiling K steps into one dispatch.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# The standalone registry module only (obs/__init__ lazy-loads the
# reconcile half, so this does NOT drag the analysis stack in here).
from torchgpipe_tpu.obs.registry import (
    MetricsRegistry,
    counter_property as _counter_property,
)
from torchgpipe_tpu.precision import DynamicLossScale

Pytree = Any

# XLA status codes that indicate infrastructure, not model, failure:
# allocator pressure (retry often succeeds after the async streams drain)
# and torn data movement.
_TRANSIENT_XLA_CODES = ("RESOURCE_EXHAUSTED", "DATA_LOSS")


def classify_error(err: BaseException) -> str:
    """``'transient'`` (retry can help) or ``'fatal'`` (re-raise now).

    Transient: ``ConnectionError`` and subclasses, ``TimeoutError``
    (covers ``socket.timeout``), and XLA runtime errors carrying
    ``RESOURCE_EXHAUSTED`` / ``DATA_LOSS`` codes.  Fatal: everything
    else — including :class:`~torchgpipe_tpu.distributed.context.
    PeerDiedError` (a dead rank leaves stale channel state; restart the
    worker, don't retry the step — see
    ``DistributedGPipe.recv_timeout``'s contract).
    """
    from torchgpipe_tpu.distributed.context import PeerDiedError

    if isinstance(err, PeerDiedError):
        return "fatal"
    if isinstance(err, (ConnectionError, TimeoutError)):
        return "transient"
    if type(err).__name__ == "XlaRuntimeError" or isinstance(
        err, jax.errors.JaxRuntimeError
    ):
        msg = str(err)
        if any(code in msg for code in _TRANSIENT_XLA_CODES):
            return "transient"
    return "fatal"


@dataclasses.dataclass(frozen=True)
class GuardPolicy:
    """Knobs for :class:`StepGuard` (defaults are production-shaped)."""

    max_retries: int = 3          # transient retries per step
    backoff_base: float = 0.25    # seconds; doubles per attempt
    backoff_max: float = 8.0      # cap on a single sleep
    skip_nonfinite: bool = True   # skip-step on non-finite loss/params

    def backoff(self, attempt: int) -> float:
        return min(self.backoff_base * (2.0 ** attempt), self.backoff_max)


class GuardStats:
    """Counters the guard maintains across steps — registry-backed.

    Re-based on :class:`torchgpipe_tpu.obs.MetricsRegistry` so guard
    skips/retries export next to every other telemetry series (JSONL /
    Prometheus via ``stats.registry``), while the original attribute
    API — ``stats.steps``, ``stats.skipped``, ``stats.retries``, read
    and ``+=``-assigned as plain ints — is unchanged.  Series names are
    fixed (``guard_*``): ONE guard per shared registry (a second guard
    on the same registry writes the same series); give concurrent
    guards their own registries.
    """

    def __init__(self, registry: Any = None) -> None:
        self.registry = registry or MetricsRegistry()
        self._steps = self.registry.counter(
            "guard_steps", help="successful (applied) steps")
        self._skipped = self.registry.counter(
            "guard_skipped", help="non-finite steps skipped")
        self._retries = self.registry.counter(
            "guard_retries", help="transient retries performed")
        # Labeled error anatomy: every exception the guard sees, by the
        # classification that decided its fate and the concrete type —
        # and, for PeerDiedError, the OFFENDING RANK, so guard retries
        # and the flight-recorder postmortem dumps cross-reference the
        # same incident instead of telling disjoint stories.
        self._errors = self.registry.counter(
            "guard_errors",
            help="step exceptions seen, by classification and type",
            labels=("classification", "error"),
        )
        self._peer_died = self.registry.counter(
            "guard_peer_died",
            help="PeerDiedError occurrences by offending rank",
            labels=("rank",),
        )

    steps = _counter_property("_steps")
    skipped = _counter_property("_skipped")
    retries = _counter_property("_retries")

    def record_error(self, classification: str, err: BaseException) -> None:
        """Count one step exception under its classification/type; a
        :class:`~torchgpipe_tpu.distributed.context.PeerDiedError` also
        names its dead rank in the ``guard_peer_died`` series."""
        self._errors.inc(
            classification=classification, error=type(err).__name__
        )
        rank = getattr(err, "rank", None)
        if rank is not None:
            self._peer_died.inc(rank=str(rank))

    def __repr__(self) -> str:
        return (
            f"GuardStats(steps={self.steps}, skipped={self.skipped}, "
            f"retries={self.retries})"
        )

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, GuardStats):
            return NotImplemented
        return (self.steps, self.skipped, self.retries) == (
            other.steps, other.skipped, other.retries
        )


def _any_deleted(tree: Pytree) -> bool:
    """True if any jax array leaf was consumed by buffer donation."""
    for a in jax.tree_util.tree_leaves(tree):
        deleted = getattr(a, "is_deleted", None)
        if deleted is not None:
            try:
                if deleted():
                    return True
            except Exception:  # noqa: BLE001 — probing must never raise
                continue
    return False


def _all_finite(tree: Pytree) -> bool:
    """Finiteness of every inexact leaf, with ONE host synchronization.

    Each leaf's ``isfinite`` reduction runs on the leaf's OWN device (the
    MPMD engine's params deliberately live on different stage devices, so
    a single cross-device jit is impossible); the per-leaf boolean
    scalars then come back in one blocking ``device_get`` — the single
    host sync the guard adds per step.
    """
    flags = [
        jnp.all(jnp.isfinite(a))
        for a in jax.tree_util.tree_leaves(tree)
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.inexact)
    ]
    if not flags:
        return True
    return bool(np.all(jax.device_get(flags)))


class StepGuard:
    """Wrap a ``make_train_step``-shaped callable with skip/retry policy.

    Example::

        step = pipe.make_train_step(optax.adamw(3e-4), donate=False)
        guard = StepGuard(step, loss_scale=DynamicLossScale())
        for batch in data:
            loss, params, opt_state = guard(params, opt_state, x, y)
            # a skipped step returns (nan_loss, params, opt_state) unchanged;
            # guard.stats.skipped counts them, guard.loss_scale backs off.

    ``finite_of(outputs) -> pytree`` overrides what the finiteness check
    covers (default: the ENTIRE output tuple, so NaNs in extras — e.g. a
    stateful model's updated running statistics — trigger the skip too).
    ``on_event(kind, info)`` observes ``'skip'`` / ``'retry'`` decisions
    (logging, metrics).

    Steps that thread extra mutable state (``GPipe.make_train_step``'s
    ``step(params, opt_state, state, x, y) -> (loss, p, o, state, aux)``)
    must tell the guard which INPUT positions carry it, or a skipped
    step would hand back state computed from the poisoned batch::

        guard = StepGuard(step, extra_state_argnums=(2,))
        # on skip, outputs[3] (the new state) is replaced by the state
        # the caller passed in at position 2 — positions map in order
        # onto outputs[3:].
    """

    def __init__(
        self,
        step: Callable[..., Tuple],
        *,
        loss_scale: Optional[DynamicLossScale] = None,
        policy: Optional[GuardPolicy] = None,
        finite_of: Optional[Callable[[Tuple], Pytree]] = None,
        extra_state_argnums: Tuple[int, ...] = (),
        classify: Callable[[BaseException], str] = classify_error,
        sleep: Callable[[float], None] = time.sleep,
        on_event: Optional[Callable[[str, dict], None]] = None,
        registry: Any = None,
    ) -> None:
        self._step = step
        self.loss_scale = loss_scale
        self.policy = policy or GuardPolicy()
        self._finite_of = finite_of
        self.extra_state_argnums = tuple(extra_state_argnums)
        self._classify = classify
        self._sleep = sleep
        self._on_event = on_event
        # ``registry`` (torchgpipe_tpu.obs.MetricsRegistry) shares the
        # guard's counters with the rest of the run's telemetry; None
        # gives the stats their own private registry (legacy shape).
        self.stats = GuardStats(registry)

    def _event(self, kind: str, **info: Any) -> None:
        if self._on_event is not None:
            self._on_event(kind, info)

    def __call__(self, params: Pytree, opt_state: Pytree, *args: Any,
                 **kwargs: Any) -> Tuple:
        out = self._call_with_retries(params, opt_state, *args, **kwargs)
        if not (isinstance(out, tuple) and len(out) >= 3):
            raise TypeError(
                "StepGuard expects the wrapped step to return "
                "(loss, new_params, new_opt_state, *extras) — the "
                "make_train_step shape — got "
                f"{type(out).__name__} of length "
                f"{len(out) if isinstance(out, tuple) else 'n/a'}"
            )
        loss = out[0]
        megastep = int(getattr(self._step, "megastep", 1) or 1)
        if megastep > 1:
            # A megastep step already ran the skip-step INSIDE its scan
            # (the engines' traced all-finite check gates the carry per
            # inner step — an UNCONDITIONAL property of the compiled
            # program; ``GuardPolicy.skip_nonfinite`` only controls the
            # K=1 host-side check and cannot reach inside) and reports
            # the per-step mask as its LAST output.  The guard's job
            # shrinks to the scan boundary: fold the mask into the
            # statistics — skips that HAPPENED are always counted, so
            # no optimizer step vanishes from the accounting whatever
            # the policy says — and back the loss scale off once per
            # megastep containing any skip: the documented granularity
            # change (docs/robustness.md).  The whole-output finite
            # check would be wrong here: the loss VECTOR legitimately
            # carries the skipped steps' non-finite losses while the
            # params stayed protected.
            mask = np.asarray(jax.device_get(out[-1])).astype(bool).ravel()
            skipped = int(mask.size - mask.sum())
            self.stats.steps += int(mask.sum())
            if skipped:
                self.stats.skipped += skipped
                if self.loss_scale is not None:
                    self.loss_scale = self.loss_scale.bad()
                self._event(
                    "skip", loss=loss, skipped=self.stats.skipped,
                    megastep=megastep,
                    loss_scale=(
                        self.loss_scale.scale
                        if self.loss_scale is not None else None
                    ),
                )
            elif self.loss_scale is not None:
                self.loss_scale = self.loss_scale.ok()
            return out
        if self.policy.skip_nonfinite:
            checked = (
                self._finite_of(out) if self._finite_of is not None else out
            )
            # The ONE host sync the guard adds per step.
            if not _all_finite(checked):
                self.stats.skipped += 1
                if self.loss_scale is not None:
                    self.loss_scale = self.loss_scale.bad()
                self._event(
                    "skip",
                    loss=loss,
                    skipped=self.stats.skipped,
                    loss_scale=(
                        self.loss_scale.scale
                        if self.loss_scale is not None
                        else None
                    ),
                )
                # Skip-step: hand back the state the caller passed in —
                # including threaded extras the step replaced (their input
                # positions map in order onto outputs[3:]).
                fargs = (params, opt_state) + args
                extras = list(out[3:])
                for k, argnum in enumerate(self.extra_state_argnums):
                    extras[k] = fargs[argnum]
                return (loss, params, opt_state) + tuple(extras)
        if self.loss_scale is not None:
            self.loss_scale = self.loss_scale.ok()
        self.stats.steps += 1
        return out

    def _call_with_retries(self, *args: Any, **kwargs: Any) -> Tuple:
        attempt = 0
        while True:
            try:
                return self._step(*args, **kwargs)
            except Exception as err:  # noqa: BLE001 — classified below
                classification = self._classify(err)
                self.stats.record_error(classification, err)
                if (
                    classification != "transient"
                    or attempt >= self.policy.max_retries
                ):
                    if attempt > 0 and hasattr(err, "add_note"):
                        err.add_note(
                            f"StepGuard: giving up after {attempt} transient "
                            "retr" + ("y" if attempt == 1 else "ies")
                        )
                    raise
                if _any_deleted(args) or _any_deleted(kwargs):
                    # The failed attempt already CONSUMED donated input
                    # buffers (donate=True is both engines' default, and
                    # XLA honors it on accelerators even when the step
                    # later fails) — re-invoking would crash with a cryptic
                    # "Array has been deleted".  Convert the dead end into
                    # a didactic error instead.
                    if hasattr(err, "add_note"):
                        err.add_note(
                            "StepGuard: cannot retry — the failed attempt "
                            "donated its input buffers to XLA; build the "
                            "step with make_train_step(..., donate=False) "
                            "to make it retryable"
                        )
                    raise
                delay = self.policy.backoff(attempt)
                attempt += 1
                self.stats.retries += 1
                self._event(
                    "retry", attempt=attempt, delay=delay,
                    error=type(err).__name__,
                )
                self._sleep(delay)
