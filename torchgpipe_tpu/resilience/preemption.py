"""Cooperative preemption: SIGTERM/SIGINT -> checkpoint-and-exit between
steps.

Preemptible TPU VMs deliver a SIGTERM and a short grace window before the
machine vanishes; an untouched Python default would kill the process
mid-step, mid-checkpoint, mid-anything.  :class:`PreemptionHandler`
converts the signal into a FLAG the training loop polls at its one safe
point — the step boundary — so the run saves a consistent snapshot and
exits cleanly, to be resumed by the next incarnation via
:meth:`~torchgpipe_tpu.resilience.checkpoint.CheckpointManager.
restore_latest`.

The canonical loop (docs/robustness.md)::

    with PreemptionHandler() as stop:
        for step in range(start, total):
            loss, params, opt_state = guard(params, opt_state, *batch)
            if step % save_every == 0 or stop.check(step):
                mgr.save(step, {"params": params, "opt": opt_state, ...})
            if stop.preempted:
                break   # clean exit inside the grace window

Notes:

* ``check(step)`` also honors a simulated preemption injected via
  :func:`torchgpipe_tpu.resilience.faults.inject` (``preempt_at_step=k``)
  — the CI stand-in for a real SIGTERM, used by the kill-and-resume
  round-trip tests.
* Signals are swallowed only ONCE per signal number: a second SIGINT
  raises ``KeyboardInterrupt`` (the impatient-operator contract), a
  second SIGTERM stays cooperative (the platform usually follows up with
  SIGKILL anyway).
* ``signal.signal`` works from the main thread only; constructing the
  handler elsewhere raises — poll :mod:`faults` or call
  :meth:`simulate` from worker threads instead.
"""

from __future__ import annotations

import collections
import signal
import threading
import weakref
from types import FrameType
from typing import Any, Callable, Deque, Dict, Iterable, Optional

from torchgpipe_tpu.resilience import faults


class PreemptionHandler:
    """Latches termination signals into a flag polled between steps."""

    def __init__(
        self,
        signals: Iterable[int] = (signal.SIGTERM, signal.SIGINT),
    ) -> None:
        self.signals = tuple(signals)
        self._flag = threading.Event()
        self.signum: Optional[int] = None
        self._seen: Dict[int, int] = {}
        self._previous: Dict[int, Any] = {}
        self._installed = False
        # Hooks not yet delivered.  A deque because popleft() is one
        # atomic C call: signal handlers run between bytecodes on this
        # same thread, so claim-then-invoke with plain ints could
        # double-fire a hook when a signal lands mid-claim — popping
        # hands each hook to exactly one _fire frame.
        self._pending: Deque[Callable[[], Optional[Callable[[], None]]]] \
            = collections.deque()

    # ------------------------------------------------------------------ #
    # lifecycle                                                          #
    # ------------------------------------------------------------------ #

    def install(self) -> "PreemptionHandler":
        if self._installed:
            return self
        for s in self.signals:
            self._previous[s] = signal.signal(s, self._on_signal)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for s, prev in self._previous.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):  # interpreter shutting down
                pass
        self._previous.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc: Any) -> None:
        self.uninstall()

    # ------------------------------------------------------------------ #
    # signal path                                                        #
    # ------------------------------------------------------------------ #

    def _on_signal(self, signum: int, frame: Optional[FrameType]) -> None:
        del frame
        self._seen[signum] = self._seen.get(signum, 0) + 1
        self.signum = signum
        self._flag.set()
        self._fire()
        if signum == signal.SIGINT and self._seen[signum] > 1:
            raise KeyboardInterrupt  # second ctrl-C: stop waiting politely

    def simulate(self) -> None:
        """Set the flag programmatically (tests, custom watchdogs)."""
        self._flag.set()
        self._fire()

    # ------------------------------------------------------------------ #
    # drain hooks                                                        #
    # ------------------------------------------------------------------ #

    def add_callback(self, fn: Callable[[], None]) -> None:
        """Register a drain hook fired at most ONCE — when preemption
        first latches (signal, :meth:`simulate`, or a fault-injected
        step), or immediately if it already has.  Hooks may run in
        signal context — they must only flip flags / enqueue work (the
        serving engine's ``request_drain`` contract), never block or
        touch device state; exceptions are swallowed (a broken observer
        must not lose the preemption grace window).

        Bound methods are held by ``weakref.WeakMethod``: a
        process-lifetime handler must not pin every engine ever wired
        to it (a dead serving engine's hook is skipped, and the engine
        — KV pool included — stays collectable).  Plain functions,
        closures, and bound methods WeakMethod cannot hold (C-level
        methods, ``__slots__`` receivers without ``__weakref__``) are
        held strongly."""
        ref: Callable[[], Optional[Callable[[], None]]]
        try:
            ref = weakref.WeakMethod(fn)  # type: ignore[arg-type]
        except TypeError:
            ref = lambda fn=fn: fn  # noqa: E731 — uniform resolve shape
        self._pending.append(ref)
        if self._flag.is_set():
            self._fire()

    def _fire(self) -> None:
        # At-most-once per CALLBACK, not per handler: a hook registered
        # after the flag latched still gets its delivery, and each
        # popleft() hands its hook to exactly one frame even when a
        # signal re-enters this loop mid-iteration.
        while True:
            try:
                ref = self._pending.popleft()
            except IndexError:
                return
            fn = ref()
            if fn is None:          # referent collected: skip
                continue
            try:
                fn()
            except Exception:  # noqa: BLE001 — see add_callback
                pass

    # ------------------------------------------------------------------ #
    # polling                                                            #
    # ------------------------------------------------------------------ #

    @property
    def preempted(self) -> bool:
        """True once a signal arrived or a preemption was simulated."""
        return self._flag.is_set()

    def check(self, step: Optional[int] = None) -> bool:
        """Poll at a step boundary.  Latches (and then reports) a
        fault-injected preemption for ``step`` as well as real signals."""
        if step is not None and faults.should_preempt(step):
            self._flag.set()
            self._fire()
        return self._flag.is_set()
