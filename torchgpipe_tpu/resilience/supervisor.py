"""Elastic training supervisor: survive losing hardware, re-absorb it.

GPipe's partitioning assumes a fixed world size for the life of a run;
real fleets don't cooperate — a rank dies (``PeerDiedError``), a rank
goes silent (a :class:`~torchgpipe_tpu.obs.flightrec.StallWatchdog`
verdict), and preempted capacity later comes back.  Every primitive for
surviving that already exists in this repo (atomic snapshots, the
certified planner, :meth:`~torchgpipe_tpu.gpipe.GPipe.repartition`);
:class:`Supervisor` is the closed loop that composes them:

1. **Checkpoint from the survivors, or restore the last good
   snapshot.**  A COOPERATIVE death lands at a megastep boundary
   (``faults.inject(die_at_megastep=...)``, or a stall verdict acted on
   between rounds), where training state is consistent — the
   supervisor snapshots it before resizing.  A MID-STEP death
   (``PeerDiedError`` out of the step itself) means the dead rank held
   unsaved state: the supervisor restores the newest verified snapshot
   instead and rewinds the step counter to it.
2. **Re-plan under the surviving world size.**  The surviving rank
   count picks the largest allowed stage count (``stage_counts``), and
   :func:`torchgpipe_tpu.analysis.planner.plan` searches balance cuts
   at that count — the measured :class:`~torchgpipe_tpu.obs.costmodel.
   CostModel` rides along when fresh (``plan`` itself falls back to
   analytic pricing when stale).  Only a candidate that is feasible
   AND certified is ever applied — no certified plan, no resume
   (:class:`SupervisorError`), never a guessed cut.
3. **Rebuild and resume.**  The new pipe is constructed at the chosen
   plan (the ``apply_plan`` carry rules: fused + megastep survive where
   the plan supports them), params/state re-split onto the new cut via
   :meth:`~torchgpipe_tpu.gpipe.GPipe.repartition`, and training
   continues.  Optimizer state is carried BITWISE when the cut is
   unchanged and honestly re-initialized when it is not (per-stage
   optimizer trees mirror a whole stage, not a layer — the documented
   ``repartition`` contract); every :class:`ResizeEvent` records which.

The symmetric scale-up path re-absorbs returned capacity
(:meth:`Supervisor.return_capacity`) at the next megastep boundary —
same plan/certify/repartition pipeline, direction ``up``.

Every decision is observable: ``supervisor_resizes_total{direction}`` /
``supervisor_restores_total`` counters and the ``supervisor_world_size``
gauge on the metrics registry, a ``supervisor_resize`` event (and a
ring dump) on the flight recorder — so a resize and the transport
flapping that caused it (``retries_total{rank}``) cross-reference one
incident.  See docs/robustness.md ("Elastic training") for the worked
4→2→4 walkthrough and the loss-continuity caveats.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchgpipe_tpu.resilience import faults
from torchgpipe_tpu.resilience.checkpoint import CheckpointManager

Pytree = Any


class SupervisorError(RuntimeError):
    """The supervisor could not resume (no certified plan at any
    allowed stage count, no usable snapshot, an unattributable hang)."""


@dataclasses.dataclass
class ResizeEvent:
    """One world-size change the supervisor performed."""

    step: int
    from_stages: int
    to_stages: int
    reason: str        # "rank-death:R" | "stall-watchdog:R" |
    #                    "peer-died:R" | "capacity-returned"
    action: str        # "checkpoint" (survivors consistent) | "restore"
    certified: bool    # the applied plan passed planner certification
    balance: List[int]
    opt_state: str     # "carried" (bitwise) | "reinit" (cut changed)


@dataclasses.dataclass
class SupervisorResult:
    """What :meth:`Supervisor.run` hands back."""

    pipe: Any
    params: Tuple[Pytree, ...]
    state: Tuple[Pytree, ...]
    opt_state: Tuple[Pytree, ...]
    losses: List[float]
    steps: int
    events: List[ResizeEvent]


def _even_balance(n_layers: int, n_stages: int) -> Tuple[int, ...]:
    """The deterministic near-even cut of ``n_layers`` over
    ``n_stages`` (earlier stages take the remainder)."""
    base, rem = divmod(n_layers, n_stages)
    return tuple(base + (1 if j < rem else 0) for j in range(n_stages))


def _tree_stack(trees: Sequence[Pytree]) -> Pytree:
    return jax.tree_util.tree_map(lambda *a: jnp.stack(a), *trees)


class Supervisor:
    """The elastic training loop (module docstring).  Typical use::

        sup = Supervisor(pipe, optimizer, loss_fn, batch_fn,
                         checkpoint=CheckpointManager(ckpt_dir),
                         world=range(4), stage_counts=(4, 2, 1))
        result = sup.run(steps, params, state)

    ``batch_fn(step)`` returns the ``(x, target)`` minibatch for one
    optimizer step — it must be a pure function of ``step`` so a
    restore-and-rewind replays the same data.  ``world`` is the rank
    ids currently holding capacity; ``stage_counts`` the stage counts
    the run may legally resize to (largest fitting the survivors
    wins; default: every count from the initial one down to 1).

    The loop advances one megastep (``pipe.megastep`` optimizer steps)
    per round; every boundary checks cooperative deaths
    (``faults.should_die_at_megastep``), acted-on stall verdicts
    (:meth:`report_stall`) and pending capacity returns
    (:meth:`return_capacity`).  A ``PeerDiedError`` (or a stall-
    attributed ``TimeoutError``) raised out of the step itself takes
    the restore path instead.
    """

    def __init__(
        self,
        pipe: Any,
        optimizer: Any,
        loss_fn: Any,
        batch_fn: Callable[[int], Tuple[Pytree, Pytree]],
        *,
        checkpoint: CheckpointManager,
        world: Sequence[int],
        stage_counts: Optional[Sequence[int]] = None,
        hbm_budget_bytes: Optional[int] = None,
        cost_model: Optional[Any] = None,
        planner_options: Optional[Dict[str, Any]] = None,
        checkpoint_every: Optional[int] = None,
        registry: Optional[Any] = None,
        recorder: Optional[Any] = None,
        rng: Optional[jax.Array] = None,
    ) -> None:
        self.pipe = pipe
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.batch_fn = batch_fn
        self.checkpoint = checkpoint
        self.world: List[int] = list(world)
        if not self.world:
            raise ValueError("a supervisor needs at least one rank")
        n0 = len(pipe.balance)
        self.stage_counts: List[int] = sorted(
            set(int(c) for c in (stage_counts or range(n0, 0, -1))),
            reverse=True,
        )
        if any(c < 1 for c in self.stage_counts):
            raise ValueError("stage_counts must be >= 1")
        self.hbm_budget_bytes = int(
            hbm_budget_bytes
            if hbm_budget_bytes is not None
            else (getattr(pipe, "hbm_budget_bytes", None) or (64 << 30))
        )
        self.cost_model = cost_model
        self.planner_options = dict(planner_options or {})
        self.checkpoint_every = checkpoint_every
        self.registry = registry
        self.recorder = recorder
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.events: List[ResizeEvent] = []
        self._pending: List[int] = []
        self._stall_rank: Optional[int] = None
        self._c_resizes = (
            registry.counter(
                "supervisor_resizes_total",
                help="world-size changes the supervisor performed",
                labels=("direction",),
            ) if registry is not None else None
        )
        self._c_restores = (
            registry.counter(
                "supervisor_restores_total",
                help="mid-step deaths recovered by snapshot restore",
            ) if registry is not None else None
        )
        self._g_world = (
            registry.gauge(
                "supervisor_world_size",
                help="stage count the supervised run currently trains at",
            ) if registry is not None else None
        )
        if self._g_world is not None:
            self._g_world.set(float(n0))

    # ------------------------------------------------------------------ #
    # external signals                                                   #
    # ------------------------------------------------------------------ #

    def return_capacity(self, ranks: Sequence[int]) -> None:
        """Announce returned capacity; absorbed (scale-up) at the next
        megastep boundary — never mid-megastep (the compiled K-step
        program cannot be resized from inside)."""
        for r in ranks:
            if r not in self.world and r not in self._pending:
                self._pending.append(int(r))

    def report_stall(self, rank: int) -> None:
        """A StallWatchdog verdict naming the silent rank.  Wire it as
        ``on_stall=lambda idle_s: sup.report_stall(suspect)``; the
        supervisor evicts the rank at the next boundary (cooperative
        path) or uses it to attribute a bare ``TimeoutError`` raised
        out of the step (restore path)."""
        self._stall_rank = int(rank)

    # ------------------------------------------------------------------ #
    # planning                                                           #
    # ------------------------------------------------------------------ #

    def _fit_stage_count(self) -> int:
        for c in self.stage_counts:
            if c <= len(self.world):
                return c
        raise SupervisorError(
            f"no allowed stage count {self.stage_counts} fits the "
            f"{len(self.world)} surviving rank(s)"
        )

    def _balance_candidates(self, n_stages: int) -> List[Tuple[int, ...]]:
        n_layers = len(self.pipe.layers)
        if n_stages > n_layers:
            raise SupervisorError(
                f"cannot cut {n_layers} layers into {n_stages} stages"
            )
        return [_even_balance(n_layers, n_stages)]

    def plan_for(self, n_stages: int) -> Optional[Any]:
        """The certified plan the supervisor would resume at
        ``n_stages``, or None when no candidate certifies.  Public so
        tests and oracles resize through the exact same search."""
        from torchgpipe_tpu.analysis import planner

        x, _ = self.batch_fn(0)
        report = planner.plan(
            self.pipe, x, self.hbm_budget_bytes,
            balance_options=self._balance_candidates(n_stages),
            chunks_options=[int(self.pipe.chunks)],
            cost_model=self.cost_model,
            **self.planner_options,
        )
        for p in report.candidates:
            if (
                p.feasible and p.certified
                and p.balance is not None
                and len(p.balance) == n_stages
            ):
                return p
        return None

    def _build(self, plan: Any) -> Any:
        """Rebuild the pipe at a certified plan — the ``apply_plan``
        carry rules (fused + megastep survive where the plan supports
        them), with stages wrapped onto the surviving devices."""
        from torchgpipe_tpu.gpipe import GPipe

        pipe = self.pipe
        fused = (
            bool(getattr(pipe, "fused", False))
            and plan.schedule == "gpipe"
            and plan.checkpoint != "offload"
        )
        built = GPipe(
            pipe.layers,
            balance=list(plan.balance),
            chunks=int(plan.chunks),
            checkpoint=plan.checkpoint,
            schedule=plan.schedule,
            loss_reduction=(
                pipe.loss_reduction if plan.schedule == "1f1b" else None
            ),
            devices=list(pipe.devices),
            fused=fused,
            megastep=(int(getattr(pipe, "megastep", 1)) if fused else 1),
            tracer=(None if fused else getattr(pipe, "tracer", None)),
            hbm_budget_bytes=getattr(pipe, "hbm_budget_bytes", None),
        )
        built.compute_dtype = pipe.compute_dtype
        return built

    # ------------------------------------------------------------------ #
    # snapshots                                                          #
    # ------------------------------------------------------------------ #

    def _save(
        self,
        step: int,
        params: Tuple[Pytree, ...],
        state: Tuple[Pytree, ...],
        opt_state: Tuple[Pytree, ...],
    ) -> None:
        self.checkpoint.save(
            step,
            {"params": params, "state": state, "opt": opt_state},
            world_size=len(self.pipe.balance),
            balance=list(self.pipe.balance),
        )

    def _template(
        self, balance: Sequence[int]
    ) -> Dict[str, Tuple[Pytree, ...]]:
        """A ``{params, state, opt}`` template tree at ``balance`` —
        the structure a snapshot taken under that cut restores into
        (values come from the snapshot; the throwaway init only
        supplies shapes)."""
        from torchgpipe_tpu.gpipe import GPipe

        tmp = GPipe(
            self.pipe.layers, balance=list(balance),
            devices=[self.pipe.devices[0]],
        )
        x, _ = self.batch_fn(0)
        in_spec = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype),
            x,
        )
        params_t, state_t = tmp.init(self._rng, in_spec)
        opt_t = tmp.init_opt_state(self.optimizer, params_t)
        return {"params": params_t, "state": state_t, "opt": opt_t}

    # ------------------------------------------------------------------ #
    # resize                                                             #
    # ------------------------------------------------------------------ #

    def _record_resize(self, event: ResizeEvent) -> None:
        self.events.append(event)
        if event.to_stages < event.from_stages:
            direction = "down"
        elif event.to_stages > event.from_stages:
            direction = "up"
        else:
            direction = "same"  # rank lost, stage count survived
        if self._c_resizes is not None:
            self._c_resizes.inc(direction=direction)
        if event.action == "restore" and self._c_restores is not None:
            self._c_restores.inc()
        if self._g_world is not None:
            self._g_world.set(float(event.to_stages))
        if self.recorder is not None:
            try:
                self.recorder.record(
                    "supervisor_resize",
                    detail=(
                        f"from={event.from_stages} to={event.to_stages} "
                        f"reason={event.reason} action={event.action} "
                        f"certified={event.certified} "
                        f"balance={event.balance} "
                        f"opt_state={event.opt_state}"
                    ),
                )
                if hasattr(self.recorder, "dump"):
                    self.recorder.dump()
            except Exception:  # noqa: BLE001 - telemetry is best-effort
                pass

    def _resize(
        self,
        step: int,
        params: Tuple[Pytree, ...],
        state: Tuple[Pytree, ...],
        opt_state: Tuple[Pytree, ...],
        *,
        reason: str,
        action: str,
    ) -> Tuple[Any, Tuple, Tuple, Tuple, int]:
        """Re-plan, rebuild, carry; returns ``(pipe, params, state,
        opt_state, resume_step)``.  ``action='checkpoint'`` snapshots
        the live (consistent) state first and carries it forward;
        ``action='restore'`` discards the live state for the newest
        verified snapshot and rewinds to its step."""
        new_n = self._fit_stage_count()
        plan = self.plan_for(new_n)
        tried = [new_n]
        while plan is None:
            smaller = [c for c in self.stage_counts if c < tried[-1]]
            if not smaller:
                raise SupervisorError(
                    f"no certified plan at any allowed stage count "
                    f"(tried {tried}) — refusing to resume uncertified"
                )
            tried.append(smaller[0])
            plan = self.plan_for(smaller[0])
        old_n = len(self.pipe.balance)
        old_balance = list(self.pipe.balance)

        if action == "checkpoint":
            # The survivors' state is consistent (megastep boundary):
            # snapshot it under the OLD cut before anything changes.
            self._save(step, params, state, opt_state)
            resume_step = step
            src_params, src_state, src_opt = params, state, opt_state
        elif action == "restore":
            probe = self.checkpoint.restore_latest()
            if probe is None:
                raise SupervisorError(
                    "restore-path recovery needs a snapshot, and no "
                    "verified one exists"
                )
            rec_balance = probe.metadata.get("balance") or old_balance
            strict = self.checkpoint.restore_step(
                probe.step, self._template(rec_balance)
            )
            resume_step = strict.step
            src_params = strict.tree["params"]
            src_state = strict.tree["state"]
            src_opt = strict.tree["opt"]
            old_balance = [int(b) for b in rec_balance]
        else:
            raise ValueError(f"unknown resize action {action!r}")

        new_pipe = self._build(plan)
        same_cut = old_balance == list(new_pipe.balance)
        if same_cut:
            new_params = new_pipe.place(tuple(src_params))
            new_state = new_pipe.place(tuple(src_state))
            new_opt = new_pipe.place(tuple(src_opt))
            opt_how = "carried"
        else:
            # The repartition carry: per-stage per-layer lists flatten
            # to layer order and re-split on the new cut; optimizer
            # state mirrors a whole stage and is honestly re-initialized
            # (momentum restarts; params and loss trajectory continue).
            new_params = new_pipe.place(new_pipe.repartition(src_params))
            new_state = new_pipe.place(new_pipe.repartition(src_state))
            new_opt = new_pipe.init_opt_state(self.optimizer, new_params)
            opt_how = "reinit"
        event = ResizeEvent(
            step=resume_step, from_stages=old_n,
            to_stages=len(new_pipe.balance), reason=reason, action=action,
            certified=bool(plan.feasible and plan.certified),
            balance=[int(b) for b in new_pipe.balance], opt_state=opt_how,
        )
        self.pipe = new_pipe
        self._record_resize(event)
        return new_pipe, new_params, new_state, new_opt, resume_step

    # ------------------------------------------------------------------ #
    # the loop                                                           #
    # ------------------------------------------------------------------ #

    def _round(
        self,
        train_step: Any,
        params: Tuple[Pytree, ...],
        opt_state: Tuple[Pytree, ...],
        state: Tuple[Pytree, ...],
        step: int,
    ) -> Tuple[List[float], Tuple, Tuple, Tuple]:
        K = max(int(getattr(self.pipe, "megastep", 1) or 1), 1)
        if K > 1:
            pairs = [self.batch_fn(step + i) for i in range(K)]
            x = _tree_stack([p[0] for p in pairs])
            y = _tree_stack([p[1] for p in pairs])
            losses, params, opt_state, state, _aux, _finite = train_step(
                params, opt_state, state, x, y
            )
            return (
                [float(v) for v in np.asarray(losses)],
                params, opt_state, state,
            )
        x, y = self.batch_fn(step)
        loss, params, opt_state, state, _aux = train_step(
            params, opt_state, state, x, y
        )
        return [float(loss)], params, opt_state, state

    def run(
        self,
        steps: int,
        params: Tuple[Pytree, ...],
        state: Tuple[Pytree, ...],
        opt_state: Optional[Tuple[Pytree, ...]] = None,
    ) -> SupervisorResult:
        """Train ``steps`` optimizer steps under supervision (class
        docstring).  Returns the final engine and state plus the full
        loss trajectory and every resize performed."""
        if opt_state is None:
            opt_state = self.pipe.init_opt_state(self.optimizer, params)
        train_step = self.pipe.make_train_step(self.optimizer, self.loss_fn)
        losses: List[float] = []
        step = 0
        self._save(step, params, state, opt_state)
        while step < steps:
            K = max(int(getattr(self.pipe, "megastep", 1) or 1), 1)
            megasteps = step // K
            dead = [
                r for r in self.world
                if faults.should_die_at_megastep(r, megasteps)
            ]
            if (
                self._stall_rank is not None
                and self._stall_rank in self.world
            ):
                dead.append(self._stall_rank)
            reason: Optional[str] = None
            if dead:
                for r in dead:
                    if r in self.world:
                        self.world.remove(r)
                kind = (
                    "stall-watchdog" if dead == [self._stall_rank]
                    else "rank-death"
                )
                reason = f"{kind}:{','.join(str(r) for r in dead)}"
                self._stall_rank = None
            elif self._pending:
                self.world.extend(self._pending)
                self._pending = []
                if self._fit_stage_count() != len(self.pipe.balance):
                    reason = "capacity-returned"
            if reason is not None:
                _, params, state, opt_state, step = self._resize(
                    step, params, state, opt_state,
                    reason=reason, action="checkpoint",
                )
                del losses[step:]
                train_step = self.pipe.make_train_step(
                    self.optimizer, self.loss_fn
                )
                continue
            try:
                new_losses, params, opt_state, state = self._round(
                    train_step, params, opt_state, state, step
                )
            except TimeoutError as err:
                # PeerDiedError subclasses TimeoutError and names the
                # rank; a bare timeout is attributable only through a
                # stall verdict (report_stall) — unattributed, it
                # re-raises rather than guessing which rank to evict.
                rank = getattr(err, "rank", None)
                if rank is None:
                    rank = self._stall_rank
                if rank is None:
                    raise
                self._stall_rank = None
                if rank in self.world:
                    self.world.remove(rank)
                _, params, state, opt_state, step = self._resize(
                    step, params, state, opt_state,
                    reason=f"peer-died:{rank}", action="restore",
                )
                del losses[step:]
                train_step = self.pipe.make_train_step(
                    self.optimizer, self.loss_fn
                )
                continue
            losses.extend(new_losses)
            step += K
            cadence = (
                self.checkpoint_every
                if self.checkpoint_every is not None else K
            )
            if cadence > 0 and step % cadence == 0:
                self._save(step, params, state, opt_state)
        return SupervisorResult(
            pipe=self.pipe, params=params, state=state,
            opt_state=opt_state, losses=losses, steps=step,
            events=self.events,
        )


__all__ = [
    "ResizeEvent",
    "Supervisor",
    "SupervisorError",
    "SupervisorResult",
]
