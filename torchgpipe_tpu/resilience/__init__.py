"""Resilience: crash-safe checkpoints, guarded steps, preemption, chaos.

The paper-scale promise of pipeline parallelism ("training giant models",
GPipe arXiv:1811.06965; torchgpipe arXiv:2004.09910) is hours-to-weeks
jobs on preemptible accelerator fleets — which only pays off if the run
*survives*: a run must be restartable (atomic versioned checkpoints),
self-healing (skip NaN steps, retry transient infrastructure errors),
preemption-aware (SIGTERM -> checkpoint-and-exit), and all of it testable
(deterministic fault injection).  Each concern is one module:

* :mod:`~torchgpipe_tpu.resilience.checkpoint` —
  :class:`CheckpointManager`: write-to-temp + fsync + rename snapshots
  with a checksummed JSON manifest, keep-last-k GC, and
  ``restore_latest()`` that skips corrupt/partial snapshots.  One format
  over both engines (flat npz like ``utils.serialization.save``, or
  orbax-sharded like ``save_sharded``).
* :mod:`~torchgpipe_tpu.resilience.guard` — :class:`StepGuard`:
  one-scalar-sync non-finite detection with skip-step +
  :class:`~torchgpipe_tpu.precision.DynamicLossScale` backoff, and
  bounded-exponential retry of errors :func:`classify_error` deems
  transient (model bugs re-raise immediately).
* :mod:`~torchgpipe_tpu.resilience.preemption` —
  :class:`PreemptionHandler`: SIGTERM/SIGINT latched into a
  between-steps flag for cooperative checkpoint-and-exit.
* :mod:`~torchgpipe_tpu.resilience.faults` — :func:`inject` (NaN at a
  chosen (stage, micro-batch) in either engine, simulated preemption at
  step k, cooperative rank death at a megastep boundary) and
  :class:`FaultyTransport` (drop/lose/delay/duplicate sends) — the test
  harness for the three modules above, and a user-facing chaos tool.
* :mod:`~torchgpipe_tpu.resilience.supervisor` — :class:`Supervisor`:
  the elastic closed loop over all of the above — on a dead or stalled
  rank, checkpoint from the survivors (or restore the last good
  snapshot), re-plan CERTIFIED under the surviving world size, rebuild
  via ``GPipe.repartition`` and resume; re-absorb returned capacity at
  a megastep boundary.

See docs/robustness.md for the end-to-end recovery story.
"""

from torchgpipe_tpu.resilience.checkpoint import (
    CheckpointError,
    CheckpointManager,
    Snapshot,
)
from torchgpipe_tpu.resilience.faults import (
    FaultPlan,
    FaultyTransport,
    SendFault,
    inject,
)
from torchgpipe_tpu.resilience.guard import (
    GuardPolicy,
    GuardStats,
    StepGuard,
    classify_error,
)
from torchgpipe_tpu.resilience.preemption import PreemptionHandler
from torchgpipe_tpu.resilience.supervisor import (
    ResizeEvent,
    Supervisor,
    SupervisorError,
    SupervisorResult,
)

__all__ = [
    "CheckpointError",
    "CheckpointManager",
    "Snapshot",
    "FaultPlan",
    "FaultyTransport",
    "SendFault",
    "inject",
    "GuardPolicy",
    "GuardStats",
    "StepGuard",
    "classify_error",
    "PreemptionHandler",
    "ResizeEvent",
    "Supervisor",
    "SupervisorError",
    "SupervisorResult",
]
