"""Deterministic fault injection — the chaos harness for the resilience
stack, and the test oracle for all of it.

A GPipe-class pipeline fails in a handful of characteristic ways: a cell
produces non-finite values (overflowed bfloat16 matmul, bad batch), a
transport send is lost or slow (flaky DCN link, dying peer), the VM is
preempted mid-run.  This module reproduces each of them *on demand and
deterministically*, so recovery paths can be tested in CI instead of
discovered at 3am on a pod:

* :func:`inject` — a context manager activating a :class:`FaultPlan` for
  the enclosed steps.  ``nan_at=(stage, micro_batch)`` poisons that exact
  cell's input in both engines (the MPMD per-cell scheduler hooks it
  eagerly; the SPMD fill-drain schedule compiles a masked ``jnp.where``
  keyed on the traced ``(stage, tick - stage)`` indices).
  ``preempt_at_step=k`` makes
  :meth:`~torchgpipe_tpu.resilience.preemption.PreemptionHandler.check`
  report a preemption at step ``k`` — a SIGTERM without the SIGTERM.
* :class:`FaultyTransport` — wraps a
  :class:`~torchgpipe_tpu.distributed.context.LocalTransport` /
  ``TcpTransport`` and applies :class:`SendFault` rules on ``send``:
  ``drop`` (raise ``ConnectionError`` at the sender — the retryable
  transient), ``lose`` (silently discard — the receiver-side hang that
  ``recv_timeout`` must catch), ``delay`` and ``duplicate``.

Injection is engine-level, not layer-level: user models need no
instrumentation, and the injected fault is exactly placed — the same
(stage, micro-batch) every run.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Any, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Pytree = Any


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """What to break while the plan is active (see :func:`inject`)."""

    # Poison the input of pipeline cell (stage, micro-batch) with NaNs.
    nan_at: Optional[Tuple[int, int]] = None
    # PreemptionHandler.check(step) reports True for step >= this.
    preempt_at_step: Optional[int] = None
    # Slow every cell of one stage by (stage, extra_seconds): the
    # synthetic straggler for the observe->replan loop.  Applied inside
    # the MPMD per-cell tracer's recorded span (Timeline.record's
    # ``settle``), so the stage is genuinely slower on the wall clock
    # AND the measured reconciliation sees it — a traced pipe is
    # required (the chaos targets the measurement path by design).
    slow_at: Optional[Tuple[int, float]] = None
    # Kill serving-fleet replica ``replica`` at its engine step ``step``
    # (replica, step): the fleet router checks :func:`should_die` before
    # every replica iteration and raises
    # :class:`torchgpipe_tpu.fleet.router.ReplicaDied` — the cooperative
    # replica-death the failover tests drive (mid-generation when
    # ``step`` lands between a request's first and last token).  Like
    # ``slow_at`` it is host-side only: traces nothing, never tokens
    # the compiled-program caches (:func:`plan_token` stays None).
    die_at_step: Optional[Tuple[int, int]] = None
    # Kill TRAINING rank ``rank`` at megastep boundary ``k`` —
    # ``die_at_step``'s training twin: (rank, k).  The resilience
    # supervisor checks :func:`should_die_at_megastep` at every
    # megastep boundary (the only place training state is consistent —
    # checkpoint/preemption/replan hooks share that cadence) and treats
    # a hit as that rank's cooperative death: checkpoint from the
    # survivors, re-plan under the surviving world size, resume.  Like
    # ``die_at_step`` it is host-side only: traces nothing, never
    # tokens the compiled-program caches (:func:`plan_token` stays
    # None), so the kill-and-resume tests run without recompiles or
    # real process kills.
    die_at_megastep: Optional[Tuple[int, int]] = None
    # Slow one serving-fleet replica by (replica, extra_seconds) per
    # engine step — ``slow_at``'s serving twin: the fleet router sleeps
    # ``extra_seconds`` BEFORE each of that replica's engine steps, so
    # every token it emits is wall-clock late and the per-replica
    # TTFT/TPOT histograms genuinely degrade — the deterministic
    # latency fault the SLO burn-rate gate (``tools/slo_verify.py``)
    # drives.  Host-side only: traces nothing, never tokens the
    # compiled-program caches (:func:`plan_token` stays None).
    slow_replica_at: Optional[Tuple[int, float]] = None
    # A PUBLISHED PARAM VERSION that degrades on swap: (replica_index,
    # version).  While serving replica ``replica_index`` runs at param
    # version ``version`` (``Engine.version``, set by ``swap_params``),
    # the fleet router sleeps ``bad_version_delay`` extra seconds before
    # each of its engine steps — the deterministic quality/SLO
    # regression a live rollout must catch, and the rollback witness
    # ``tools/rollout_verify.py`` drives (SLO burn on the updated
    # replica → RolloutController rolls the fleet back to version N).
    # Latency-shaped ON PURPOSE: token VALUES stay bitwise (greedy
    # streams still match the cold-start gate), only the wall clock
    # degrades, exactly like ``slow_replica_at``.  Host-side only:
    # traces nothing, never tokens the compiled-program caches
    # (:func:`plan_token` stays None).
    bad_version_at: Optional[Tuple[int, int]] = None
    # Extra seconds per step while ``bad_version_at`` matches.
    bad_version_delay: float = 0.05


_lock = threading.Lock()
_active: Optional[FaultPlan] = None
# Monotonic epoch, bumped on every activation/deactivation: engines that
# CACHE compiled programs key them by plan_token() so a program traced with
# an injection is never reused once the plan is gone (and vice versa).
_epoch: int = 0


@contextlib.contextmanager
def inject(
    *,
    nan_at: Optional[Tuple[int, int]] = None,
    preempt_at_step: Optional[int] = None,
    slow_at: Optional[Tuple[int, float]] = None,
    die_at_step: Optional[Tuple[int, int]] = None,
    die_at_megastep: Optional[Tuple[int, int]] = None,
    slow_replica_at: Optional[Tuple[int, float]] = None,
    bad_version_at: Optional[Tuple[int, int]] = None,
    bad_version_delay: float = 0.05,
) -> Iterator[FaultPlan]:
    """Activate a :class:`FaultPlan` for the enclosed block.

    Plans do not nest (the inner activation wins would be ambiguous); a
    second concurrent ``inject`` raises.
    """
    global _active, _epoch
    plan = FaultPlan(nan_at=nan_at, preempt_at_step=preempt_at_step,
                     slow_at=slow_at, die_at_step=die_at_step,
                     die_at_megastep=die_at_megastep,
                     slow_replica_at=slow_replica_at,
                     bad_version_at=bad_version_at,
                     bad_version_delay=bad_version_delay)
    with _lock:
        if _active is not None:
            raise RuntimeError(
                "a fault plan is already active; fault injections do not "
                "nest"
            )
        _active = plan
        _epoch += 1
    try:
        yield plan
    finally:
        with _lock:
            _active = None
            _epoch += 1


def active_plan() -> Optional[FaultPlan]:
    """The currently injected plan, or None."""
    return _active


def plan_token() -> Optional[int]:
    """Cache key for compiled programs: an epoch unique to this activation
    when the active plan can alter a TRACED program (``nan_at``), else
    None.  Inert-for-tracing plans (``preempt_at_step`` only) must not
    token — they would force two full recompiles of the pipelined step
    (entering and leaving the context) for a fault the trace never sees."""
    plan = _active
    return _epoch if plan is not None and plan.nan_at is not None else None


def poison(tree: Pytree) -> Pytree:
    """Every floating leaf replaced by NaNs (shape/dtype preserved)."""
    return jax.tree_util.tree_map(
        lambda a: jnp.full_like(a, jnp.nan)
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
        else a,
        tree,
    )


def corrupt_cell_input(stage: int, microbatch: int, tree: Pytree) -> Pytree:
    """MPMD engine hook: called with CONCRETE cell indices by the per-cell
    schedulers; poisons the input iff the active plan names this cell."""
    plan = _active
    if plan is None or plan.nan_at != (stage, microbatch):
        return tree
    return poison(tree)


def spmd_corrupt_cell_input(
    stage: jax.Array, microbatch: jax.Array, tree: Pytree
) -> Pytree:
    """SPMD engine hook: ``stage``/``microbatch`` are TRACED lane/tick
    indices, so the poisoning compiles to a ``jnp.where`` mask.  Call only
    when a plan with ``nan_at`` is active (the caller checks at trace
    time and keys its program cache on :func:`plan_token`)."""
    plan = _active
    if plan is None or plan.nan_at is None:
        return tree
    s, i = plan.nan_at
    hit = jnp.logical_and(stage == s, microbatch == i)
    return jax.tree_util.tree_map(
        lambda a: jnp.where(hit, jnp.full_like(a, jnp.nan), a)
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
        else a,
        tree,
    )


def cell_delay_s(stage: int) -> float:
    """Extra per-cell seconds the active plan injects into ``stage``
    (0.0 without a matching ``slow_at`` plan).  The MPMD per-cell
    schedulers pass this as ``Timeline.record(..., settle=)``, so the
    slowdown both delays the run and lands INSIDE the measured span —
    the deterministic straggler the observe->replan tests drive.  Like
    ``preempt_at_step`` it traces nothing, so it never tokens the
    compiled-program caches (:func:`plan_token` stays None)."""
    plan = _active
    if plan is None or plan.slow_at is None or plan.slow_at[0] != stage:
        return 0.0
    return float(plan.slow_at[1])


def should_die(replica: int, step: int) -> bool:
    """True iff the active plan kills serving replica ``replica`` at or
    before its engine step ``step`` — the fleet router's cooperative
    death check (``Router.step`` raises ``ReplicaDied`` on a hit).
    Host-side only: inert for tracing, so compiled-program caches are
    never invalidated by entering/leaving the plan."""
    plan = _active
    return (
        plan is not None
        and plan.die_at_step is not None
        and plan.die_at_step[0] == replica
        and step >= plan.die_at_step[1]
    )


def should_die_at_megastep(rank: int, megasteps: int) -> bool:
    """True iff the active plan kills TRAINING rank ``rank`` at or
    before megastep boundary ``megasteps`` (completed megasteps) — the
    resilience supervisor's cooperative death check, ``die_at_step``'s
    training twin.  Host-side only: inert for tracing, so compiled
    -program caches are never invalidated by entering/leaving the plan
    (:func:`plan_token` stays None)."""
    plan = _active
    return (
        plan is not None
        and plan.die_at_megastep is not None
        and plan.die_at_megastep[0] == rank
        and megasteps >= plan.die_at_megastep[1]
    )


def replica_delay_s(replica: int) -> float:
    """Extra per-step seconds the active plan injects into serving
    replica ``replica`` (0.0 without a matching ``slow_replica_at``
    plan).  The fleet router sleeps this long BEFORE each of that
    replica's engine steps, so every token it emits is wall-clock late
    — the deterministic latency fault the SLO burn-rate monitor acts
    on.  Like ``die_at_step`` it is host-side only and never tokens the
    compiled-program caches (:func:`plan_token` stays None)."""
    plan = _active
    if (
        plan is None
        or plan.slow_replica_at is None
        or plan.slow_replica_at[0] != replica
    ):
        return 0.0
    return float(plan.slow_replica_at[1])


def bad_version_delay_s(replica: int, version: int) -> float:
    """Extra per-step seconds the active plan injects into serving
    replica ``replica`` WHILE it runs at param version ``version``
    (0.0 without a matching ``bad_version_at`` plan) —
    ``slow_replica_at``'s rollout twin.  The fleet router consults the
    replica engine's current ``version`` attribute before each step, so
    the fault activates the moment ``swap_params`` lands the bad
    version and deactivates the moment a rollback swaps it away: the
    deterministic SLO regression :class:`fleet.rollout.RolloutController`'s
    health gate must catch.  Host-side only: traces nothing, never
    tokens the compiled-program caches (:func:`plan_token` stays
    None)."""
    plan = _active
    if (
        plan is None
        or plan.bad_version_at is None
        or plan.bad_version_at != (replica, version)
    ):
        return 0.0
    return float(plan.bad_version_delay)


def should_preempt(step: int) -> bool:
    """True iff the active plan simulates a preemption at/before ``step``."""
    plan = _active
    return (
        plan is not None
        and plan.preempt_at_step is not None
        and step >= plan.preempt_at_step
    )


# --------------------------------------------------------------------- #
# transport faults                                                      #
# --------------------------------------------------------------------- #


@dataclasses.dataclass
class SendFault:
    """One matching rule applied by :class:`FaultyTransport` on ``send``.

    ``None`` fields are wildcards.  ``times`` bounds how often the rule
    fires (-1 = every match); after that the send passes through clean —
    which is what makes drop-then-retry deterministic.
    """

    action: str  # 'drop' | 'lose' | 'delay' | 'duplicate'
    dst: Optional[str] = None
    kind: Any = None
    index: Optional[int] = None
    times: int = 1
    delay_s: float = 0.05
    fired: int = 0

    _ACTIONS = ("drop", "lose", "delay", "duplicate")

    def __post_init__(self) -> None:
        if self.action not in self._ACTIONS:
            raise ValueError(
                f"action must be one of {self._ACTIONS}, got {self.action!r}"
            )

    def matches(self, dst: str, kind: Any, index: int) -> bool:
        if self.times >= 0 and self.fired >= self.times:
            return False
        return (
            (self.dst is None or self.dst == dst)
            and (self.kind is None or self.kind == kind)
            and (self.index is None or self.index == index)
        )


class FaultyTransport:
    """Wrap any transport (Local/Tcp) with deterministic send-side faults.

    ``drop`` raises ``ConnectionError`` at the sender — the transient
    class :func:`torchgpipe_tpu.resilience.guard.classify_error` retries.
    ``lose`` swallows the message silently (the receiver must catch it via
    ``recv_timeout``).  ``delay`` sleeps before delivering; ``duplicate``
    delivers twice.  Everything else (register/unregister/close/is_alive)
    delegates to the wrapped transport.

    ``hang_at=(kind, index)`` is the HANG fault: a send matching that
    mailbox key blocks forever — the wedged-peer failure mode that never
    raises, which ``drop``/``lose`` cannot reproduce (the sender
    continues past a lose; a real hang pins the sender's schedule too).
    It is the first-class witness for stall-watchdog and postmortem
    tests, replacing ad-hoc sleeps.  Cooperatively interruptible:
    :meth:`release` unblocks every hung sender (which then returns
    WITHOUT delivering — the message was lost to the hang); hung test
    threads must be daemons or released in teardown.  A hang is
    transport-level and traces nothing, so it never tokens the compiled
    -program caches (:func:`plan_token` stays None — inert plans don't
    invalidate programs).
    """

    def __init__(
        self,
        inner: Any,
        faults: Sequence[SendFault] = (),
        *,
        hang_at: Optional[Tuple[Any, int]] = None,
    ) -> None:
        self.inner = inner
        self.faults: List[SendFault] = list(faults)
        self.hang_at = hang_at
        self.log: List[Tuple[str, str, Any, int]] = []  # (action, dst, kind, i)
        self._hang_release = threading.Event()

    def add(self, fault: SendFault) -> "FaultyTransport":
        self.faults.append(fault)
        return self

    def release(self) -> None:
        """Unblock every sender currently hung by ``hang_at`` (their
        messages stay undelivered) and let future matches pass through."""
        self._hang_release.set()

    def send(self, dst: str, kind: Any, index: int, payload: Any) -> None:
        if (
            self.hang_at is not None
            and self.hang_at == (kind, index)
            and not self._hang_release.is_set()
        ):
            self.log.append(("hang", dst, kind, index))
            self._hang_release.wait()  # block until cooperatively released
            return  # the hung message is never delivered
        sends = 1
        for f in self.faults:
            if not f.matches(dst, kind, index):
                continue
            f.fired += 1
            self.log.append((f.action, dst, kind, index))
            if f.action == "drop":
                raise ConnectionError(
                    f"fault injection: dropped send of {kind!r}[{index}] "
                    f"to {dst!r}"
                )
            if f.action == "lose":
                return  # silently discarded
            if f.action == "delay":
                time.sleep(f.delay_s)
            elif f.action == "duplicate":
                sends += 1
        for _ in range(sends):
            self.inner.send(dst, kind, index, payload)

    def __getattr__(self, name: str) -> Any:
        return getattr(self.inner, name)
