"""Crash-safe, versioned training checkpoints.

The persistence primitives in :mod:`torchgpipe_tpu.utils.serialization`
write ONE artifact (a flat ``.npz`` or an orbax tree); a long run needs
more: snapshots that an interrupted write can never corrupt, a history so
a bad snapshot can be skipped, and garbage collection so the history does
not eat the disk.  :class:`CheckpointManager` supplies that layer:

* **Atomic**: each snapshot is staged in a hidden temp directory in the
  SAME filesystem, every file fsync'd, the JSON manifest written LAST,
  and the directory renamed into place — a crash at any point leaves
  either the previous complete snapshot set or one invisible temp dir,
  never a half-written ``step_*`` that :func:`restore_latest` could trust.
* **Verified**: the manifest records a CRC-32 checksum, shape and dtype
  per array (npz backend) and per file (sharded backend); restore
  re-hashes and silently skips any snapshot that fails — including
  truncation *after* a successful write (disk corruption, partial copy).
* **Versioned + GC'd**: snapshots live under ``step_<n>``;
  ``keep_last_k`` complete snapshots are retained, older ones deleted
  only after a NEWER complete snapshot exists.
* **One format, both engines**: the payload is any pytree of arrays —
  a ``GPipe.state_dict`` flat dict, an ``SpmdGPipe`` params tree,
  optimizer state, rng keys — flattened to the same
  ``jax.tree_util.keystr`` naming :mod:`utils.serialization` uses.
  ``sharded=True`` stores the tree through orbax instead (each host
  writes its own shards; see :func:`utils.serialization.save_sharded`),
  under the same manifest/GC/restore protocol.

Typical loop (see docs/robustness.md)::

    mgr = CheckpointManager("ckpts", keep_last_k=3)
    snap = mgr.restore_latest(template={"params": params, "opt": opt_state,
                                        "step": jnp.zeros((), jnp.int32)})
    start = int(snap.tree["step"]) + 1 if snap else 0
    ...
    mgr.save(step, {"params": params, "opt": opt_state,
                    "step": jnp.asarray(step)},
             metadata={"loss_scale": guard.loss_scale.state_dict()})
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

Pytree = Any

_FORMAT_VERSION = 1
_MANIFEST = "manifest.json"
_NPZ = "state.npz"
_SHARDED = "sharded"
_STEP_PREFIX = "step_"
_TMP_PREFIX = ".tmp-"


class CheckpointError(RuntimeError):
    """A checkpoint operation failed (bad arguments, no usable snapshot
    when one was required, ...)."""


@dataclasses.dataclass(frozen=True)
class Snapshot:
    """A restored checkpoint: the payload tree, its step and metadata."""

    step: int
    tree: Pytree
    metadata: Dict[str, Any]
    path: str


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def _flatten(tree: Pytree) -> Dict[str, np.ndarray]:
    """Pytree -> flat ``{keystr: host ndarray}`` (the serialization naming)."""
    out: Dict[str, np.ndarray] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = jax.tree_util.keystr(path) or "."
        if key in out:
            raise CheckpointError(f"duplicate tree key {key!r}")
        out[key] = np.asarray(leaf)
    return out


def _unflatten_like(template: Pytree, flat: Dict[str, np.ndarray]) -> Pytree:
    """Rebuild ``template``'s structure with leaves from ``flat``; strict
    (missing/extra keys and shape mismatches raise, the
    ``load_state_dict(strict=True)`` contract)."""
    remaining = dict(flat)
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths:
        key = jax.tree_util.keystr(path) or "."
        if key not in remaining:
            raise CheckpointError(f"checkpoint is missing key {key!r}")
        arr = remaining.pop(key)
        want = tuple(np.shape(leaf))
        if tuple(arr.shape) != want:
            raise CheckpointError(
                f"shape mismatch for {key!r}: saved {tuple(arr.shape)}, "
                f"template expects {want}"
            )
        leaves.append(arr.astype(np.asarray(leaf).dtype))
    if remaining:
        raise CheckpointError(
            f"unexpected keys in checkpoint: {sorted(remaining)[:5]}"
            + ("..." if len(remaining) > 5 else "")
        )
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms without O_RDONLY dirs; durability best-effort
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _barrier(tag: str) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"resilience.checkpoint:{tag}")


class CheckpointManager:
    """Atomic, versioned, checksummed snapshots under one directory.

    Multi-host: every process calls :meth:`save`/:meth:`restore_latest`
    with the same arguments.  For ``sharded=True`` each process writes its
    own orbax shards; all filesystem surgery (rename, manifest, GC) is
    done by process 0 only, fenced by global barriers — the same protocol
    as :func:`utils.serialization.save_sharded`.  The npz backend
    host-gathers through ``np.asarray`` and is meant for single-process
    runs (every process would write the same bytes; harmless but wasteful
    on shared storage).
    """

    def __init__(self, directory: str, *, keep_last_k: int = 3) -> None:
        if keep_last_k < 1:
            raise ValueError("keep_last_k must be >= 1")
        self.directory = os.path.abspath(os.fspath(directory))
        self.keep_last_k = keep_last_k
        if jax.process_index() == 0:
            os.makedirs(self.directory, exist_ok=True)
        _barrier("init")

    # ------------------------------------------------------------------ #
    # save                                                               #
    # ------------------------------------------------------------------ #

    def save(
        self,
        step: int,
        tree: Pytree,
        *,
        metadata: Optional[Dict[str, Any]] = None,
        sharded: bool = False,
        world_size: Optional[int] = None,
        balance: Optional[List[int]] = None,
    ) -> str:
        """Write snapshot ``step_<step>`` atomically; returns its path.

        ``metadata`` must be JSON-serializable (step counters, rng seeds,
        loss-scale state, ...); arrays belong in ``tree``.

        ``world_size``/``balance`` record the stage count and layer cut
        the snapshot was taken under (stored in the manifest metadata).
        An elastic run restoring into a DIFFERENT world size can then be
        detected up front (:meth:`restore_latest` with ``world_size=``)
        and routed through ``GPipe.repartition`` explicitly, instead of
        failing deep inside ``_unflatten_like`` on a shape mismatch.
        """
        if step < 0:
            raise ValueError(f"step must be >= 0, got {step}")
        final = self._step_dir(step)
        tmp = os.path.join(
            self.directory, f"{_TMP_PREFIX}{_STEP_PREFIX}{step:010d}"
        )
        meta = dict(metadata or {})
        if world_size is not None:
            meta["world_size"] = int(world_size)
        if balance is not None:
            meta["balance"] = [int(b) for b in balance]
        manifest: Dict[str, Any] = {
            "format": _FORMAT_VERSION,
            "step": int(step),
            "backend": _SHARDED if sharded else "npz",
            "metadata": meta,
        }
        if jax.process_index() == 0:
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
        _barrier("pre-save")

        if sharded:
            import orbax.checkpoint as ocp

            with ocp.StandardCheckpointer() as ckptr:
                ckptr.save(os.path.join(tmp, _SHARDED), tree)
                ckptr.wait_until_finished()
            _barrier("post-write")
            if jax.process_index() == 0:
                manifest["files"] = self._hash_dir(tmp, fsync=True)
        else:
            flat = _flatten(tree)
            manifest["arrays"] = {
                k: {
                    "crc32": _crc(a),
                    "shape": list(a.shape),
                    "dtype": str(a.dtype),
                }
                for k, a in flat.items()
            }
            if jax.process_index() == 0:
                npz_path = os.path.join(tmp, _NPZ)
                with open(npz_path, "wb") as f:
                    np.savez(f, **flat)
                    f.flush()
                    os.fsync(f.fileno())

        if jax.process_index() == 0:
            # Manifest LAST, then the tmp dir itself, then the swap: its
            # presence inside a step_* dir certifies a complete write.
            man_path = os.path.join(tmp, _MANIFEST)
            with open(man_path, "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            _fsync_dir(tmp)
            if os.path.exists(final):
                old = final + ".old"
                shutil.rmtree(old, ignore_errors=True)
                os.rename(final, old)
                os.rename(tmp, final)
                shutil.rmtree(old, ignore_errors=True)
            else:
                os.rename(tmp, final)
            _fsync_dir(self.directory)
            self._gc()
        _barrier("post-swap")
        return final

    # ------------------------------------------------------------------ #
    # restore                                                            #
    # ------------------------------------------------------------------ #

    def restore_latest(
        self,
        template: Optional[Pytree] = None,
        *,
        world_size: Optional[int] = None,
    ) -> Optional[Snapshot]:
        """The newest snapshot that passes verification, or ``None``.

        Corrupt or partial snapshots (missing/unparseable manifest,
        checksum/shape/dtype mismatch, truncated files) are SKIPPED in
        favor of the next older one — the property that makes
        save-every-k-steps a durable strategy rather than a single point
        of failure.

        With ``template`` the payload is rebuilt into its structure
        (required for ``sharded`` snapshots, where it also supplies the
        shardings — pass the live initialized tree); without it the flat
        ``{keystr: ndarray}`` dict is returned.

        ``world_size=`` declares the stage count the CALLER is restoring
        into.  A snapshot whose manifest records a different
        ``world_size`` (see :meth:`save`) is returned FLAT — its metadata
        carries the recorded ``balance`` — so an elastic caller can
        rebuild under the old cut and route through
        ``GPipe.repartition`` explicitly, instead of ``template``
        unflattening failing on a per-stage shape mismatch.  Snapshots
        written without the record restore through ``template`` as
        before (no way to tell; the strict path's shape check still
        protects the caller)."""
        for step in sorted(self.steps(), reverse=True):
            use_template = template
            if world_size is not None and template is not None:
                recorded = self._recorded_world_size(step)
                if recorded is not None and recorded != int(world_size):
                    use_template = None
            snap = self._try_restore(step, use_template)
            if snap is not None:
                return snap
        return None

    def restore_step(
        self, step: int, template: Optional[Pytree] = None
    ) -> Snapshot:
        """Restore one specific snapshot; raises if it fails verification."""
        snap = self._try_restore(step, template)
        if snap is None:
            raise CheckpointError(
                f"snapshot step_{step} at {self._step_dir(step)} is missing "
                "or fails verification"
            )
        return snap

    def steps(self) -> List[int]:
        """Steps with a snapshot directory present (verified or not).

        ``step_<n>.old`` counts too: a crash between the two renames of a
        same-step re-save leaves only the ``.old`` copy, and restore must
        still find it (see :meth:`_try_restore`'s fallback)."""
        return _scan_steps(self.directory)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    # ------------------------------------------------------------------ #
    # internals                                                          #
    # ------------------------------------------------------------------ #

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step:010d}")

    def _recorded_world_size(self, step: int) -> Optional[int]:
        """The ``world_size`` snapshot ``step`` was taken under, read
        from its manifest (``.old`` fallback included) without loading
        any array — ``None`` when unrecorded or unreadable."""
        primary = self._step_dir(step)
        for path in (primary, primary + ".old"):
            manifest = self._read_manifest(path)
            if manifest is not None:
                ws = manifest.get("metadata", {}).get("world_size")
                try:
                    return int(ws) if ws is not None else None
                except (TypeError, ValueError):
                    return None
        return None

    def _hash_dir(
        self, root: str, *, fsync: bool = False
    ) -> Dict[str, Dict[str, int]]:
        """CRC-32 + size per file under ``root`` (manifest excluded),
        relative paths — the sharded backend's integrity record.
        ``fsync=True`` on the save path only (durability belongs to the
        writer; restore-side verification must not pay one fsync per
        shard per probed snapshot)."""
        out: Dict[str, Dict[str, int]] = {}
        for dirpath, _, filenames in os.walk(root):
            for fn in sorted(filenames):
                if fn == _MANIFEST:
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root)
                crc = 0
                size = 0
                with open(full, "rb") as f:
                    while True:
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        crc = zlib.crc32(chunk, crc)
                        size += len(chunk)
                if fsync:
                    _fsync_file(full)
                out[rel] = {"crc32": crc, "size": size}
        return out

    def _read_manifest(self, path: str) -> Optional[Dict[str, Any]]:
        man_path = os.path.join(path, _MANIFEST)
        try:
            with open(man_path) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(manifest, dict) or manifest.get("format") != _FORMAT_VERSION:
            return None
        return manifest

    def _try_restore(
        self, step: int, template: Optional[Pytree]
    ) -> Optional[Snapshot]:
        """Verify-and-load ``step``; falls back to ``step_<n>.old`` (the
        displaced copy of a same-step re-save) when the primary is
        missing or fails verification, so a crash ANYWHERE in the
        re-save's rename sequence still leaves this step restorable."""
        primary = self._step_dir(step)
        snap = self._restore_dir(primary, step, template)
        if snap is not None:
            return snap
        return self._restore_dir(primary + ".old", step, template)

    def _restore_dir(
        self, path: str, step: int, template: Optional[Pytree]
    ) -> Optional[Snapshot]:
        manifest = self._read_manifest(path)
        if manifest is None:
            return None
        metadata = manifest.get("metadata", {})
        if manifest.get("backend") == _SHARDED:
            if template is None:
                raise CheckpointError(
                    f"snapshot step_{step} is sharded (orbax): "
                    "restore_latest needs the template tree to supply "
                    "structure and shardings"
                )
            want = manifest.get("files")
            if not isinstance(want, dict) or self._hash_dir(path) != want:
                return None
            from torchgpipe_tpu.utils.serialization import restore_sharded

            try:
                tree = restore_sharded(os.path.join(path, _SHARDED), template)
            except Exception:
                return None
            return Snapshot(step=step, tree=tree, metadata=metadata, path=path)

        want_arrays = manifest.get("arrays")
        if not isinstance(want_arrays, dict):
            return None
        try:
            with np.load(os.path.join(path, _NPZ)) as f:
                flat = {k: f[k] for k in f.files}
        except Exception:
            return None  # truncated/corrupt zip, missing file, bad member
        if set(flat) != set(want_arrays):
            return None
        for k, rec in want_arrays.items():
            a = flat[k]
            if (
                list(a.shape) != rec.get("shape")
                or str(a.dtype) != rec.get("dtype")
                or _crc(a) != rec.get("crc32")
            ):
                return None
        tree = _unflatten_like(template, flat) if template is not None else flat
        return Snapshot(step=step, tree=tree, metadata=metadata, path=path)

    def _gc(self) -> None:
        """Keep the last ``keep_last_k`` COMPLETE snapshots; also sweep
        the two kinds of crash litter: ``step_<n>.old`` copies whose
        primary is complete again (the re-save finished — the fallback
        copy is redundant), and incomplete snapshot dirs older than a
        newer complete one."""
        complete = [
            s for s in self.steps()
            if self._read_manifest(self._step_dir(s)) is not None
        ]
        for s in complete[: -self.keep_last_k]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
            shutil.rmtree(self._step_dir(s) + ".old", ignore_errors=True)
        for s in complete:
            shutil.rmtree(self._step_dir(s) + ".old", ignore_errors=True)
        # A snapshot dir WITHOUT a manifest is junk only if a newer
        # complete snapshot exists (otherwise it may be an in-flight
        # concurrent writer's — leave it).  Its .old fallback survives
        # while the step is inside the keep-last-k window (it may be the
        # only good copy); once keep_last_k NEWER complete snapshots
        # exist it is retired like any other old snapshot — otherwise
        # every mid-swap crash would leak a full snapshot forever.
        newest = complete[-1] if complete else None
        cutoff = (
            complete[-self.keep_last_k]
            if len(complete) >= self.keep_last_k
            else None
        )
        for s in self.steps():
            if newest is not None and s < newest and s not in complete:
                shutil.rmtree(self._step_dir(s), ignore_errors=True)
            if cutoff is not None and s < cutoff:
                shutil.rmtree(self._step_dir(s) + ".old", ignore_errors=True)


def _scan_steps(directory: str) -> List[int]:
    """Step numbers present under ``directory`` (``step_<n>`` and
    ``step_<n>.old``), verified or not.  Pure directory listing — safe
    from any single process of a multi-host job (no barriers)."""
    if not os.path.isdir(directory):
        return []
    out = set()
    for name in os.listdir(directory):
        if not name.startswith(_STEP_PREFIX):
            continue
        base = name[: -len(".old")] if name.endswith(".old") else name
        try:
            out.add(int(base[len(_STEP_PREFIX):]))
        except ValueError:
            continue
    return sorted(out)


def latest_step_or_none(directory: str) -> Optional[int]:
    """Peek at a checkpoint directory without constructing a manager —
    and therefore without :class:`CheckpointManager`'s collective init
    barrier, so a single rank of a multi-host job may call it freely."""
    steps = _scan_steps(os.path.abspath(os.fspath(directory)))
    return steps[-1] if steps else None
