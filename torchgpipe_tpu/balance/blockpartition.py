"""Optimal contiguous block partitioning of a cost sequence.

The reference implements the iterative heuristic from Bárány & Grinberg,
"Block Partitions of Sequences" (reference:
torchgpipe/balance/blockpartition.py:11-89).  Instead of porting that
heuristic, this module solves the underlying problem exactly: split a sequence
into ``partitions`` contiguous blocks minimizing the maximum block sum (the
pipeline's bottleneck stage), with the mean block sum as tie-breaker.  The
classic O(n²·k) dynamic program is exact and instantaneous at the scale of
layer counts (hundreds), so there is no reason to settle for a heuristic.
"""

from __future__ import annotations

from typing import List, Sequence


def solve(sequence: Sequence[float], partitions: int = 1) -> List[List[float]]:
    """Split ``sequence`` into ``partitions`` contiguous blocks minimizing the
    maximum block sum.

    Returns the blocks themselves (same convention as the reference's
    ``solve``).  Raises ``ValueError`` on an infeasible request, with the
    reference's error wording (blockpartition.py:14-18).

    Dispatches to the native C++ solver (:mod:`torchgpipe_tpu._native`) when
    available — same algorithm, same tie-breaking; measured 93x faster at
    the reference's own 370-layer ResNet-101 (115 ms -> 1.2 ms) and
    160-175x at 1000-5000 layers (867 ms -> 5.3 ms at n=1000, k=8; see
    BENCH_NOTES.md) — falling back to the Python DP below.
    """
    if partitions < 1:
        raise ValueError("partitions must be a positive integer")
    n = len(sequence)
    if n < partitions:
        raise ValueError(
            f"sequence length is less than intended partitions (sequence: {n}, "
            f"partitions: {partitions})"
        )

    from torchgpipe_tpu import _native

    native_sizes = _native.blockpartition_sizes(sequence, partitions)
    if native_sizes is not None:
        blocks: List[List[float]] = []
        i = 0
        for size in native_sizes:
            blocks.append(list(sequence[i : i + size]))
            i += size
        return blocks

    costs = [float(c) for c in sequence]
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + c)

    def block_sum(i: int, j: int) -> float:
        """Sum of costs[i:j]."""
        return prefix[j] - prefix[i]

    INF = float("inf")
    # dp[k][j] = minimal possible maximum block sum when splitting costs[:j]
    # into k blocks (each non-empty).
    dp = [[INF] * (n + 1) for _ in range(partitions + 1)]
    cut = [[0] * (n + 1) for _ in range(partitions + 1)]
    dp[0][0] = 0.0
    for k in range(1, partitions + 1):
        # Each of the remaining partitions needs at least one element.
        for j in range(k, n - (partitions - k) + 1):
            best, best_i = INF, k - 1
            for i in range(k - 1, j):
                cand = max(dp[k - 1][i], block_sum(i, j))
                if cand < best:
                    best, best_i = cand, i
            dp[k][j] = best
            cut[k][j] = best_i

    bounds = [n]
    j = n
    for k in range(partitions, 0, -1):
        j = cut[k][j]
        bounds.append(j)
    bounds.reverse()

    return [
        list(sequence[bounds[b] : bounds[b + 1]]) for b in range(partitions)
    ]


def solve_sizes(sequence: Sequence[float], partitions: int = 1) -> List[int]:
    """Like :func:`solve` but return block *lengths* — the ``balance`` list."""
    return [len(b) for b in solve(sequence, partitions)]
