"""Automatic stage balancing: per-layer costs -> exact block partition.

Reference: torchgpipe/balance/__init__.py:38-156 (``balance_by_time`` /
``balance_by_size``).  Usage::

    from torchgpipe_tpu.balance import balance_by_flops

    balance = balance_by_flops(4, layers, sample=sample)
    model = GPipe(layers, balance, chunks=8)

Two cost sources:

* **analytic** (:func:`balance_by_flops`, preferred) — per-layer
  forward+backward FLOPs from the structure-aware jaxpr walker
  (:func:`torchgpipe_tpu.analysis.jaxpr.flops_estimate`) over an
  abstract trace: no device compute, no compile, deterministic on any
  host.  This is the cost model the static planner
  (:mod:`torchgpipe_tpu.analysis.planner`) searches balance cuts with.
* **probe-based** (:func:`balance_by_time` / :func:`balance_by_size`,
  the reference lineage) — runtime timing / XLA memory analysis on a
  real device.  These remain fully supported (no warning is emitted;
  time-profiling is still the only way to capture effects the analytic
  model cannot see, e.g. a layer bottlenecked on memory bandwidth
  rather than FLOPs), but they cost real device time per call and their
  numbers vary run to run — new code should start from
  ``balance_by_flops`` and only reach for the probes when measurements
  disagree with the analytic cut.  The planner never calls them.

Either way the costs feed :func:`blockpartition.solve` — the exact
contiguous block-partition solver (minimize the bottleneck stage sum).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

from torchgpipe_tpu.balance import blockpartition
from torchgpipe_tpu.balance.profile import profile_sizes, profile_times
from torchgpipe_tpu.layers import Layer

__all__ = [
    "balance_by_flops",
    "balance_by_time",
    "balance_by_size",
    "balance_cost",
    "layer_flops",
]

Pytree = Any


def balance_cost(costs: Sequence[float], partitions: int) -> List[int]:
    """Turn per-layer costs into a balance via exact block partitioning.

    Reference: torchgpipe/balance/__init__.py:33-35.
    """
    return blockpartition.solve_sizes(costs, partitions)


def layer_flops(
    layers: Sequence[Layer],
    sample: Pytree,
    *,
    params: Optional[Sequence[Pytree]] = None,
    states: Optional[Sequence[Pytree]] = None,
) -> List[float]:
    """Per-layer forward+backward FLOPs by abstract evaluation only.

    Each layer's fwd+bwd is traced to a jaxpr at its in-chain input spec
    (specs threaded through the layer sequence with ``jax.eval_shape``,
    skip stashes included) and costed by
    :func:`torchgpipe_tpu.analysis.jaxpr.flops_estimate`.  ``params`` /
    ``states`` default to an ``eval_shape`` init — arrays are never
    materialized and no device is touched.  Layers with zero matmul/conv
    work cost 0 (the walker weighs MXU ops; elementwise glue is noise at
    partition granularity).
    """
    import jax

    from torchgpipe_tpu.analysis.jaxpr import avalify, flops_estimate
    from torchgpipe_tpu.balance.profile import _layer_fwd_bwd
    from torchgpipe_tpu.layers import sequential_init

    sample = avalify(sample)
    if params is None or states is None:
        params, states, _ = jax.eval_shape(
            lambda: sequential_init(
                list(layers), jax.random.PRNGKey(0), sample
            )
        )
    params = [avalify(p) for p in params]
    states = [avalify(s) for s in states]

    flops: List[float] = []
    skips: dict = {}
    x = sample
    for i, layer in enumerate(layers):
        pops = {k: skips[k] for k in layer.pop}
        fn = _layer_fwd_bwd(layer)
        jaxpr = jax.make_jaxpr(fn)(params[i], states[i], x, pops)
        flops.append(flops_estimate(jaxpr))
        x, stashed, _ = jax.eval_shape(fn, params[i], states[i], x, pops)
        for k in layer.pop:
            skips.pop(k, None)
        skips.update(stashed)
    return flops


def balance_by_flops(
    partitions: int,
    layers: Sequence[Layer],
    sample: Pytree,
    *,
    params: Optional[Sequence[Pytree]] = None,
    states: Optional[Sequence[Pytree]] = None,
) -> List[int]:
    """Balance by ANALYTIC per-layer fwd+bwd FLOPs — the probe-free
    replacement for :func:`balance_by_time`: same contract, but the
    costs come from :func:`layer_flops` (abstract eval, deterministic,
    zero device time) instead of wall-clock sweeps on a device.  This is
    the balance source of :func:`torchgpipe_tpu.analysis.planner.plan`.
    """
    return balance_cost(
        layer_flops(layers, sample, params=params, states=states),
        partitions,
    )


def balance_by_time(
    partitions: int,
    layers: Sequence[Layer],
    params: Sequence[Pytree],
    states: Sequence[Pytree],
    sample: Pytree,
    *,
    timeout: float = 1.0,
    device: Any = None,
) -> List[int]:
    """Balance by profiled forward+backward time per layer.

    Reference: torchgpipe/balance/__init__.py:38-77.  Probe-based: each
    call costs ``timeout`` seconds of REAL device time and its numbers
    vary with co-tenants — prefer :func:`balance_by_flops` unless you
    specifically need measured (bandwidth-bound) costs.
    """
    times = profile_times(
        layers, params, states, sample, timeout=timeout, device=device
    )
    return balance_cost(times, partitions)


def balance_by_size(
    partitions: int,
    layers: Sequence[Layer],
    params: Sequence[Pytree],
    states: Sequence[Pytree],
    sample: Pytree,
    *,
    param_scale: float = 2.0,
    device: Any = None,
) -> List[int]:
    """Balance by per-layer memory footprint (XLA memory analysis + scaled
    parameter bytes).

    Reference: torchgpipe/balance/__init__.py:80-156.  Compiles each
    layer on the target backend; for a probe-free cut use
    :func:`balance_by_flops` and let the planner's memory certification
    (:mod:`torchgpipe_tpu.analysis.planner`) check the footprint.
    """
    sizes = profile_sizes(
        layers, params, states, sample, param_scale=param_scale, device=device
    )
    return balance_cost(sizes, partitions)
