"""Automatic stage balancing: profile per-layer costs, then block-partition.

Reference: torchgpipe/balance/__init__.py:38-156 (``balance_by_time`` /
``balance_by_size``).  Usage::

    from torchgpipe_tpu.balance import balance_by_time

    balance = balance_by_time(4, layers, params, states, sample)
    model = GPipe(layers, balance, chunks=8)
"""

from __future__ import annotations

from typing import Any, List, Sequence

from torchgpipe_tpu.balance import blockpartition
from torchgpipe_tpu.balance.profile import profile_sizes, profile_times
from torchgpipe_tpu.layers import Layer

__all__ = ["balance_by_time", "balance_by_size", "balance_cost"]

Pytree = Any


def balance_cost(costs: Sequence[float], partitions: int) -> List[int]:
    """Turn per-layer costs into a balance via exact block partitioning.

    Reference: torchgpipe/balance/__init__.py:33-35.
    """
    return blockpartition.solve_sizes(costs, partitions)


def balance_by_time(
    partitions: int,
    layers: Sequence[Layer],
    params: Sequence[Pytree],
    states: Sequence[Pytree],
    sample: Pytree,
    *,
    timeout: float = 1.0,
    device: Any = None,
) -> List[int]:
    """Balance by profiled forward+backward time per layer.

    Reference: torchgpipe/balance/__init__.py:38-77.
    """
    times = profile_times(
        layers, params, states, sample, timeout=timeout, device=device
    )
    return balance_cost(times, partitions)


def balance_by_size(
    partitions: int,
    layers: Sequence[Layer],
    params: Sequence[Pytree],
    states: Sequence[Pytree],
    sample: Pytree,
    *,
    param_scale: float = 2.0,
    device: Any = None,
) -> List[int]:
    """Balance by per-layer memory footprint (XLA memory analysis + scaled
    parameter bytes).

    Reference: torchgpipe/balance/__init__.py:80-156.
    """
    sizes = profile_sizes(
        layers, params, states, sample, param_scale=param_scale, device=device
    )
    return balance_cost(sizes, partitions)
