"""Per-layer cost profiling for automatic balancing.

Reference: torchgpipe/balance/profile.py.  The reference deep-copies each
layer into a sandbox, times eager forward+backward between
``cuda.synchronize`` fences (profile.py:40-81), and sizes memory from CUDA
allocator deltas (profile.py:84-118).  TPU-native redesign:

* timing: each layer's forward+backward is JIT-compiled and timed with
  ``block_until_ready`` fences; compilation is excluded by a warmup call.
  The layer list is swept repeatedly until ``timeout`` wall-clock elapses,
  like the reference.
* memory: XLA's compiled memory analysis replaces allocator deltas —
  exact temp+output buffer sizes from the compiler, not a runtime probe.
  Parameter bytes are scaled by ``param_scale`` (optimizer head-room,
  reference balance/__init__.py:100-108).
* no sandboxing needed: layers are immutable descriptions; profiling cannot
  corrupt the user's model (the property reference profile.py:21-37 works
  hard for comes free).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from torchgpipe_tpu.layers import Layer, apply_layer

Pytree = Any


def _layer_fwd_bwd(layer: Layer) -> Callable:
    """Build a jittable forward+backward for one layer (dispatch shared with
    the engines via :func:`~torchgpipe_tpu.layers.apply_layer`)."""

    def run(params, state, x, pops):
        def f(p, xx, pp):
            skips = dict(pp)
            y, _ = apply_layer(
                layer, p, state, xx, skips, rng=jax.random.PRNGKey(0), train=True
            )
            return y, skips  # after apply_layer, skips holds the stashes

        (y, stashed), pull = jax.vjp(f, params, x, pops)
        cot = jax.tree_util.tree_map(jnp.ones_like, (y, stashed))
        grads = pull(cot)
        return y, stashed, grads

    return run


def _thread_inputs(
    layers: Sequence[Layer],
    params: Sequence[Pytree],
    states: Sequence[Pytree],
    sample: Pytree,
) -> List[Tuple[Pytree, Dict]]:
    """Concrete (input, pops) pair for every layer, obtained by running the
    chain once."""
    inputs: List[Tuple[Pytree, Dict]] = []
    skips: Dict = {}
    x = sample
    key = jax.random.PRNGKey(0)
    for i, layer in enumerate(layers):
        pops = {k: skips[k] for k in layer.pop}
        inputs.append((x, pops))
        x, _ = apply_layer(
            layers[i], params[i], states[i], x, skips, rng=key, train=True
        )
    return inputs


def profile_times(
    layers: Sequence[Layer],
    params: Sequence[Pytree],
    states: Sequence[Pytree],
    sample: Pytree,
    *,
    timeout: float = 1.0,
    device: Any = None,
) -> List[float]:
    """Per-layer forward+backward wall-clock cost (seconds, summed over
    sweeps).  Reference: torchgpipe/balance/profile.py:40-81."""
    if device is None:
        device = jax.devices()[0]
    params = jax.device_put(list(params), device)
    states = jax.device_put(list(states), device)
    sample = jax.device_put(sample, device)

    inputs = _thread_inputs(layers, params, states, sample)
    fns = [jax.jit(_layer_fwd_bwd(layer)) for layer in layers]

    # Warmup: compile everything (excluded from timing).
    for i, layer in enumerate(layers):
        x, pops = inputs[i]
        jax.block_until_ready(fns[i](params[i], states[i], x, pops))

    times = [0.0] * len(layers)
    begin = time.perf_counter()
    while time.perf_counter() - begin < timeout:
        for i in range(len(layers)):
            x, pops = inputs[i]
            t0 = time.perf_counter()
            jax.block_until_ready(fns[i](params[i], states[i], x, pops))
            times[i] += time.perf_counter() - t0
    return times


def _tree_bytes(tree: Pytree) -> int:
    return sum(
        leaf.size * leaf.dtype.itemsize for leaf in jax.tree_util.tree_leaves(tree)
    )


def profile_sizes(
    layers: Sequence[Layer],
    params: Sequence[Pytree],
    states: Sequence[Pytree],
    sample: Pytree,
    *,
    param_scale: float = 2.0,
    device: Any = None,
) -> List[int]:
    """Per-layer memory cost in bytes.

    ``param_scale`` covers optimizer state (SGD ~2-3, Adam ~4-5; reference:
    torchgpipe/balance/__init__.py:100-108).  Activation/temp memory comes
    from XLA's compiled memory analysis when available, else from output
    shape accounting.  Reference: torchgpipe/balance/profile.py:84-118.

    Fidelity caveat: the shape-accounting fallback estimates
    ``2·bytes(output) + bytes(stashed residuals)`` and IGNORES intra-layer
    temporaries (attention score tiles, im2col buffers), so it can
    understate memory-hungry layers; a :class:`UserWarning` is emitted
    once per call when any layer takes the fallback, naming which.  The
    reference's equivalent honesty is its CUDA-only guard
    (torchgpipe/balance/profile.py:84-118 — it refuses to size-profile
    without a device at all).
    """
    if device is None:
        device = jax.devices()[0]
    params = jax.device_put(list(params), device)
    states = jax.device_put(list(states), device)
    sample = jax.device_put(sample, device)

    inputs = _thread_inputs(layers, params, states, sample)
    sizes: List[int] = []
    fallback_layers: List[str] = []
    for i, layer in enumerate(layers):
        x, pops = inputs[i]
        param_bytes = _tree_bytes(params[i])
        act_bytes: Optional[int] = None
        try:
            compiled = (
                jax.jit(_layer_fwd_bwd(layer))
                .lower(params[i], states[i], x, pops)
                .compile()
            )
            ma = compiled.memory_analysis()
            if ma is not None:
                act_bytes = int(ma.temp_size_in_bytes) + int(
                    ma.output_size_in_bytes
                )
        except Exception:
            act_bytes = None
        if act_bytes is None:
            # Fallback: bytes of the layer output (the activation the
            # pipeline must hold) plus its input cotangent.
            fallback_layers.append(layer.name)
            y, stashed, grads = jax.eval_shape(
                _layer_fwd_bwd(layer), params[i], states[i], x, pops
            )
            act_bytes = 2 * _tree_bytes(y) + _tree_bytes(stashed)
        sizes.append(int(param_scale * param_bytes) + act_bytes)
    if fallback_layers:
        import warnings

        warnings.warn(
            "XLA memory_analysis() unavailable for "
            f"{len(fallback_layers)}/{len(layers)} layers "
            f"({', '.join(fallback_layers[:5])}"
            f"{', ...' if len(fallback_layers) > 5 else ''}): their sizes "
            "use coarse output-shape accounting that ignores intra-layer "
            "temporaries — balance_by_size partitions from these costs "
            "may understate memory-hungry layers",
            stacklevel=2,
        )
    return sizes
