"""Multi-process (MPMD) pipeline: one rank per OS process, one stage per rank.

Capability parity with the fork's ``DistributedGPipe``
(reference: torchgpipe/distributed/gpipe.py:75-275), re-designed:

* Each rank compiles its stage once (:class:`~torchgpipe_tpu.pipeline.StageExec`)
  and drives it over micro-batches; activations/gradients travel through a
  pluggable transport (:mod:`torchgpipe_tpu.distributed.context`) instead of
  ``torch.distributed.rpc`` with CPU staging.
* The fork's forward/backward APIs are mutually inconsistent with its own
  tests and benchmarks (SURVEY.md §2.4 warning); here the contract is fixed
  and explicit: ``forward`` returns the last rank's micro-batch outputs,
  ``loss_grads`` turns them into output cotangents, ``backward`` returns
  parameter gradients and the updated stage state.
* Activation checkpointing works in the distributed mode too (the fork's
  does not checkpoint): the rank stores inputs instead of vjp residuals and
  recomputes ahead of consuming the arriving cotangent.
* Cross-rank skip connections route point-to-point through the same
  transport (the fork cannot route @skippable tensors across ranks at all).

The GPipe fill-drain schedule *emerges* from cross-rank channel blocking,
exactly as in the reference (SURVEY.md §3.5: "fill-drain emerges from
cross-rank channel blocking, not a scheduler").
"""

from __future__ import annotations

from typing import (
    Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple,
)

import jax

from torchgpipe_tpu import microbatch
from torchgpipe_tpu.batchnorm import convert_deferred_batch_norm
from torchgpipe_tpu.checkpoint import CHECKPOINT_MODES, checkpoint_stop
from torchgpipe_tpu.layers import Layer, sequential_specs
from torchgpipe_tpu.partition import split_layers, verify_module
from torchgpipe_tpu.distributed.context import PeerDiedError
from torchgpipe_tpu.pipeline import LossGradRunner, StageExec
from torchgpipe_tpu.resilience import faults as _faults
from torchgpipe_tpu.skip import inspect_skip_layout, verify_skippables

Pytree = Any


def _recv_probing_peer(
    mailbox: Any,
    transport: Any,
    kind: Any,
    index: int,
    timeout: Optional[float],
    src_rank: int,
    workers: Sequence[str],
    recorder: Optional[Any] = None,
) -> Pytree:
    """Mailbox receive that converts a timeout into a
    :class:`~torchgpipe_tpu.distributed.context.PeerDiedError` when the
    expected sender fails the transport's liveness probe.

    A bare ``TimeoutError`` cannot distinguish "rank 2 is compiling its
    stage" from "rank 2 was OOM-killed an hour ago"; probing on timeout
    (and only then — zero steady-state cost) names the dead rank so the
    supervisor restarts the right process.  A slow-but-alive peer still
    surfaces as the original ``TimeoutError``.

    With a ``recorder`` (:class:`torchgpipe_tpu.obs.flightrec.
    FlightRecorder`) the receive becomes a pair of flight events —
    ``recv_wait`` (with the channel's mailbox depth) and ``recv_match``
    (with the measured wait) — and every failure path records its final
    event (``recv_timeout`` / ``peer_died``) and triggers
    :meth:`~torchgpipe_tpu.obs.flightrec.FlightRecorder.crash_dump`
    BEFORE raising, so the dump names the exact blocking channel.
    """
    name = workers[src_rank]
    t0 = 0.0
    if recorder is not None:
        depth = getattr(mailbox, "depth", None)
        t0 = recorder.clock()
        recorder.record(
            "recv_wait", channel=(kind, index), peer=name,
            detail=f"depth={depth(kind, index)}" if depth else "",
        )
    try:
        payload = mailbox.get(kind, index, timeout=timeout)
    except TimeoutError as err:
        probe = getattr(transport, "is_alive", None)
        if probe is not None:
            try:
                alive = bool(probe(name))
            except Exception:  # noqa: BLE001 — a broken probe must not
                alive = True   # mask the original timeout
            if not alive:
                if recorder is not None:
                    recorder.record(
                        "peer_died", channel=(kind, index), peer=name,
                        dur=recorder.clock() - t0,
                        detail=f"rank {src_rank} endpoint gone",
                    )
                    recorder.crash_dump(
                        f"peer_died rank={src_rank} "
                        f"channel={(kind, index)!r}"
                    )
                raise PeerDiedError(
                    src_rank,
                    name,
                    f"no message on channel {(kind, index)!r} within "
                    f"{timeout}s and its transport endpoint is gone",
                ) from err
        if recorder is not None:
            recorder.record(
                "recv_timeout", channel=(kind, index), peer=name,
                dur=recorder.clock() - t0,
                detail=f"timeout={timeout}s, peer alive",
            )
            recorder.crash_dump(
                f"recv_timeout channel={(kind, index)!r} "
                f"from rank {src_rank}"
            )
        raise
    if recorder is not None:
        recorder.record(
            "recv_match", channel=(kind, index), peer=name,
            dur=recorder.clock() - t0,
        )
    return payload


class DistributedGPipe:
    """One pipeline stage owned by this rank.

    Reference: torchgpipe/distributed/gpipe.py:75-194.  ``workers`` names all
    ranks in pipeline order; ``workers[rank]`` is this process, whose mailbox
    must be registered on ``transport`` (see
    :func:`torchgpipe_tpu.distributed.context.worker`).
    """

    def __init__(
        self,
        layers: Sequence[Layer],
        rank: int,
        workers: Sequence[str],
        balance: Sequence[int],
        *,
        chunks: int,
        transport: Any,
        mailbox: Any,
        device: Any = None,
        checkpoint: str = 'except_last',
        deferred_batch_norm: bool = False,
        recv_timeout: Optional[float] = None,
        first_step_grace: Optional[float] = None,
        recorder: Optional[Any] = None,
    ) -> None:
        # recv_timeout (opt-in) bounds every cross-rank receive: a dead or
        # wedged peer surfaces as a TimeoutError naming the missing channel
        # instead of hanging the pipeline forever (the reference's RPC mode
        # has no failure handling at all — torchgpipe/distributed/
        # context.py:37 TODO).  The FIRST step's receives also wait out
        # every upstream rank's one-time jit compilation, which can dwarf
        # a steady-state timeout; first_step_grace (seconds) is added to
        # recv_timeout for step 0 only, so the deadline can be tight from
        # step 1 without the first step tripping it on compile time.  A
        # first-step timeout WITHOUT a grace configured says so in the
        # error.  A TimeoutError is fatal for this rank's pipeline state:
        # channels may hold stale messages and peers hold partial sends —
        # recover by restarting the worker processes, not by retrying the
        # step.
        layers = list(layers)
        verify_module(layers)
        verify_skippables(layers)
        if len(balance) != len(workers):
            raise ValueError(
                f"balance has {len(balance)} stages but workers names "
                f"{len(workers)} ranks"
            )
        if not (0 <= rank < len(workers)):
            raise ValueError(f"rank {rank} out of range for {len(workers)} workers")
        if chunks <= 0:
            raise ValueError("number of chunks must be positive integer")
        if checkpoint not in CHECKPOINT_MODES:
            raise ValueError(
                f"checkpoint is not one of {'|'.join(CHECKPOINT_MODES)}"
            )
        if checkpoint == 'offload':
            # Accepting it would silently run the 'never' schedule with
            # every rank's residuals DEVICE-resident — the opposite of
            # what the mode promises.  Host-relocating the per-rank vjp
            # closures needs scheduler support this engine doesn't have.
            raise ValueError(
                "checkpoint='offload' is not supported by the distributed "
                "MPMD engine (per-rank residual relocation is not wired "
                "into its scheduler); use the single-process GPipe or the "
                "SPMD engine for host-offloaded residuals"
            )

        if deferred_batch_norm:
            layers = convert_deferred_batch_norm(layers, chunks)

        self.layers = layers
        self.rank = rank
        self.workers = list(workers)
        self.chunks = chunks
        self.checkpoint = checkpoint
        self.transport = transport
        self.mailbox = mailbox
        if first_step_grace is not None:
            if recv_timeout is None:
                raise ValueError(
                    "first_step_grace extends recv_timeout for the "
                    "compile-heavy first step, but recv_timeout is None "
                    "(receives already wait forever); set recv_timeout "
                    "or drop the grace"
                )
            if first_step_grace <= 0:
                raise ValueError(
                    f"first_step_grace must be positive seconds "
                    f"(got {first_step_grace!r})"
                )
        self.recv_timeout = recv_timeout
        self.first_step_grace = first_step_grace
        # Flips after the first completed forward: steady-state receives
        # never pay upstream compile time again, so the grace stops
        # applying.
        self._warmed = False
        # Flight recorder (torchgpipe_tpu.obs.flightrec.FlightRecorder):
        # every send enqueue, receive wait/match, cell completion and
        # loop boundary becomes a ring-buffer event, and the mailbox
        # records arrivals with channel depth — the black box the
        # postmortem analyzer (tools/postmortem.py) reads after a hang.
        self.recorder = recorder
        if recorder is not None and getattr(mailbox, "recorder", None) is None:
            mailbox.recorder = recorder

        partitions = split_layers(layers, balance)
        self.layout = inspect_skip_layout(partitions)
        self.partition = partitions[rank]
        self.offset = sum(balance[:rank])
        self.device = device if device is not None else jax.devices()[0]
        self.stage = StageExec(
            rank, self.partition, self.offset, self.device, self.layout
        )
        # Which rank pops / stashes each cross-stage skip key.
        self._skip_pop_rank = {
            k: self.layout.pop_stage(k) for k in self.stage.ext_stash_keys
        }
        self._skip_stash_rank = {
            k: self.layout.stash_stage(k) for k in self.stage.ext_pop_keys
        }
        self._ctx: Optional[Dict[str, Any]] = None
        self._loss_grad = LossGradRunner()
        if recorder is not None:
            # Everything the postmortem analyzer needs to rebuild this
            # schedule's event graph from the dump alone (the same
            # inputs analysis.events.events_for reads off a live pipe).
            recorder.set_meta(
                engine="distributed",
                rank=rank,
                worker=self.workers[rank],
                workers=list(self.workers),
                chunks=chunks,
                checkpoint=checkpoint,
                skips=[
                    [str(key), src, dst]
                    for key, (src, dst) in sorted(
                        self.layout.by_key.items(),
                        key=lambda kv: str(kv[0]),
                    )
                    if src != dst
                ],
            )
            if recorder.rank is None:
                recorder.rank = rank
            if recorder.worker is None:
                recorder.worker = self.workers[rank]

    # ------------------------------------------------------------------ #

    @property
    def is_first(self) -> bool:
        return self.rank == 0

    @property
    def is_last(self) -> bool:
        return self.rank == len(self.workers) - 1

    def _effective_timeout(self) -> Optional[float]:
        """The receive deadline for the CURRENT step: ``recv_timeout``
        plus ``first_step_grace`` while the pipeline is still cold (the
        first step's receives wait out upstream jit compilation too)."""
        if self.recv_timeout is None:
            return None
        if not self._warmed and self.first_step_grace is not None:
            return self.recv_timeout + self.first_step_grace
        return self.recv_timeout

    def _first_step_hint(self, err: TimeoutError) -> TimeoutError:
        """A first-step timeout with NO grace configured is ambiguous —
        the deadline may simply have measured the upstream rank's
        one-time jit compile.  Say so in the error instead of letting
        the user chase a phantom hang."""
        if self._warmed or self.first_step_grace is not None:
            return err
        return TimeoutError(
            f"{err} (this was the FIRST step: the wait includes the "
            "upstream rank's one-time jit compilation, which can exceed "
            "any steady-state deadline — pass first_step_grace=<compile "
            "budget seconds> to extend recv_timeout for step 0 only, or "
            "recv_timeout=None to wait compiles out)"
        )

    def _recv(self, kind: Any, index: int, src_rank: int) -> Pytree:
        """Deadline-bounded mailbox receive placed on this rank's device.

        ``src_rank`` names the expected sender; on timeout it is probed
        for liveness so a dead peer raises a clean
        :class:`~torchgpipe_tpu.distributed.context.PeerDiedError` naming
        the rank instead of an anonymous timeout."""
        try:
            payload = _recv_probing_peer(
                self.mailbox, self.transport, kind, index,
                self._effective_timeout(), src_rank, self.workers,
                recorder=self.recorder,
            )
        except PeerDiedError:
            raise
        except TimeoutError as err:
            raise self._first_step_hint(err) from err
        return jax.device_put(payload, self.device)

    def _send(self, dst_rank: int, kind: Any, index: int,
              payload: Pytree) -> None:
        """Transport send with a ``send`` flight event recorded FIRST —
        a send that then hangs or dies in the transport leaves its
        enqueue on the ring (the sender-side half the postmortem pairs
        with the receiver's ``mail_put`` arrival)."""
        dst = self.workers[dst_rank]
        if self.recorder is not None:
            self.recorder.record("send", channel=(kind, index), peer=dst)
        try:
            self.transport.send(dst, kind, index, payload)
        except Exception as err:
            if self.recorder is not None:
                self.recorder.record(
                    "send_fail", channel=(kind, index), peer=dst,
                    detail=type(err).__name__,
                )
            raise

    def init(
        self, rng: jax.Array, in_spec: Pytree
    ) -> Tuple[List[Pytree], List[Pytree]]:
        """Initialize THIS rank's partition only.

        Uses the same per-layer rng folding as
        :func:`~torchgpipe_tpu.layers.sequential_init`, so all ranks together
        reproduce exactly the single-process model's parameters — the
        transparency oracle holds across process boundaries.  Shape
        propagation through earlier ranks' layers is abstract (no FLOPs, no
        memory).
        """
        from torchgpipe_tpu.utils import host_device

        with host_device():
            specs = sequential_specs(self.layers, in_spec)
            params, state = [], []
            for li, layer in enumerate(self.partition):
                g = self.offset + li
                p, s = layer.init(jax.random.fold_in(rng, g), specs[g])
                params.append(p)
                state.append(s)
        return (
            jax.device_put(params, self.device),
            jax.device_put(state, self.device),
        )

    # ------------------------------------------------------------------ #

    def forward(
        self,
        params: Sequence[Pytree],
        state: Sequence[Pytree],
        batch: Optional[Pytree] = None,
        *,
        rng: Optional[jax.Array] = None,
        train: bool = True,
    ) -> Optional[List[Pytree]]:
        """Run this rank's stage over all micro-batches.

        Rank 0 scatters ``batch``; other ranks pass ``batch=None`` and pull
        inputs from their mailbox (reference:
        torchgpipe/distributed/gpipe.py:159-178).  Returns the per-micro-batch
        outputs on the last rank, else ``None``.
        """
        rec = self.recorder
        if rec is not None:
            # Step boundary FIRST — before the meta exchange — so one
            # recorded step is everything from here through backward_end:
            # the postmortem's frontier window (a ring holding several
            # steps must not let a past step's cells mask the current
            # step's frontier).
            rec.record("forward_begin", detail=f"train={train}")
        if self.is_first:
            if batch is None:
                raise ValueError("rank 0 must be given the input batch")
            microbatch.check(batch)
            mbatches = microbatch.scatter(batch, self.chunks)
            m = len(mbatches)
            # scatter() may produce fewer micro-batches than ``chunks``
            # (ceil-sized chunk semantics, microbatch.chunk_sizes); every rank
            # must agree on m or downstream ranks would block forever waiting
            # for micro-batches that never come.  Channels are FIFO per key,
            # so index 0 is safe across steps.
            for r in range(1, len(self.workers)):
                self._send(r, "meta", 0, m)
        else:
            if batch is not None:
                raise ValueError("only rank 0 feeds the input batch")
            mbatches = None
            try:
                m = int(
                    _recv_probing_peer(
                        self.mailbox, self.transport, "meta", 0,
                        self._effective_timeout(), 0, self.workers,
                        recorder=self.recorder,
                    )
                )
            except PeerDiedError:
                raise
            except TimeoutError as err:
                raise self._first_step_hint(err) from err

        if rec is not None:
            # The agreed micro-batch count, recorded once it is known
            # (after the meta broadcast/receive) — what the postmortem
            # rebuilds the step's event graph with.
            rec.record("forward_plan", detail=f"m={m}")
        stop = checkpoint_stop(self.checkpoint, m, train=train)
        stage = self.stage
        cur_state = list(state)
        pulls: Dict[int, Any] = {}
        saved: Dict[int, Any] = {}
        outs: List[Pytree] = []

        for i in range(m):
            if self.is_first:
                x = mbatches[i]
            else:
                x = self._recv("forward", i, self.rank - 1)
            x = _faults.corrupt_cell_input(self.rank, i, x)
            skips_in = {
                k: self._recv(("skip", k), i, self._skip_stash_rank[k])
                for k in stage.ext_pop_keys
            }
            rng_i = jax.random.fold_in(rng, i) if rng is not None else None
            t_cell = rec.clock() if rec is not None else 0.0
            if train and i < stop:
                y, ext, new_state = stage.fwd_ckpt(
                    params, cur_state, x, skips_in, rng_i, 1.0 / m
                )
                saved[i] = (x, skips_in, list(cur_state), rng_i)
            elif train:
                y, ext, new_state, pull = stage.fwd_vjp(
                    params, cur_state, x, skips_in, rng_i, 1.0 / m
                )
                pulls[i] = pull
            else:
                y, ext, new_state = stage.fwd_eval(
                    params, cur_state, x, skips_in, rng_i, 1.0 / m
                )
            if rec is not None:
                # Dispatch-granularity duration (JAX is async; the
                # transport's host staging is what forces completion) —
                # honest for ordering and for the straggler MEDIANS the
                # postmortem compares across ranks.
                rec.record("fwd", stage=self.rank, mb=i,
                           dur=rec.clock() - t_cell)
            cur_state = list(new_state)
            for k, v in ext.items():
                self._send(self._skip_pop_rank[k], ("skip", k), i, v)
            if self.is_last:
                outs.append(y)
            else:
                self._send(self.rank + 1, "forward", i, y)
        if rec is not None:
            rec.record("forward_end", detail=f"m={m}")

        if not train:
            # Eval has no backward leg: everything this rank's receives
            # can block on has compiled once — the grace stops applying.
            # (A train-mode step stays cold until backward completes:
            # step 0's backward waits out DOWNSTREAM compiles too.)
            self._warmed = True
        self._ctx = {
            "m": m,
            "pulls": pulls,
            "saved": saved,
            "params": params,
            "state": list(cur_state),
            "train": train,
        }
        return outs if self.is_last else None

    # ------------------------------------------------------------------ #

    def loss_grads(
        self,
        outputs: Sequence[Pytree],
        target: Pytree,
        loss_fn: Callable,
    ) -> Tuple[jax.Array, List[Pytree], Any]:
        """Last-rank helper: mini-batch loss + per-micro-batch output
        cotangents + ``loss_fn`` aux (or None).

        The loss sees the *gathered* output (transparency with the
        un-pipelined model); its gradient is split back per micro-batch.  The
        reference computes per-micro-batch losses in the driver instead
        (benchmarks/distributed/accuracy/main.py:307-313) — gathering first
        keeps mean-reduction semantics independent of ragged chunk sizes.
        """
        if not self.is_last:
            raise RuntimeError("loss_grads is only meaningful on the last rank")
        return self._loss_grad(list(outputs), target, loss_fn)

    def backward(
        self, grad_outputs: Optional[Sequence[Pytree]] = None
    ) -> Tuple[List[Pytree], List[Pytree]]:
        """Reverse schedule over micro-batches.

        The last rank passes the output cotangents from :meth:`loss_grads`;
        other ranks pass ``None`` and pull cotangents from the mailbox
        (reference: torchgpipe/distributed/gpipe.py:180-194, done there with
        backward hooks harvesting input grads).  Returns
        ``(param_grads, new_state)`` for this rank's partition.
        """
        if self._ctx is None:
            raise RuntimeError("backward called before forward")
        ctx = self._ctx
        self._ctx = None
        if not ctx["train"]:
            raise RuntimeError("backward after an eval-mode forward")
        m = ctx["m"]
        stage = self.stage
        acc: Optional[Pytree] = None

        if self.is_last:
            if grad_outputs is None:
                raise RuntimeError(
                    "the last rank must pass the output cotangents "
                    "(see DistributedGPipe.loss_grads)"
                )
            grad_outputs = list(grad_outputs)
        elif grad_outputs is not None:
            raise ValueError(
                "only the last rank takes output cotangents; other ranks "
                "receive theirs from the next rank's backward"
            )

        rec = self.recorder
        if rec is not None:
            rec.record("backward_begin", detail=f"m={m}")
        for i in reversed(range(m)):
            if self.is_last:
                gy = grad_outputs[i]
            else:
                gy = self._recv("backward", i, self.rank + 1)
            gext = {
                k: self._recv(("skip_grad", k), i, self._skip_pop_rank[k])
                for k in stage.ext_stash_keys
            }
            t_cell = rec.clock() if rec is not None else 0.0
            if i in ctx["saved"]:
                x, skips_in, state_in, rng_i = ctx["saved"].pop(i)
                # Recompute-ahead (reference: torchgpipe/checkpoint.py:1-19).
                _, _, _, pull = stage.fwd_recompute(
                    ctx["params"], state_in, x, skips_in, rng_i,
                    1.0 / ctx["m"],
                )
            else:
                pull = ctx["pulls"].pop(i)
            gparams, gx, gsk_in = stage.bwd(pull, (gy, gext))
            if rec is not None:
                rec.record("bwd", stage=self.rank, mb=i,
                           dur=rec.clock() - t_cell)
            acc = gparams if acc is None else stage.accum(acc, gparams)
            if not self.is_first:
                self._send(self.rank - 1, "backward", i, gx)
            for k, g in gsk_in.items():
                self._send(self._skip_stash_rank[k], ("skip_grad", k), i, g)
        if rec is not None:
            rec.record("backward_end", detail=f"m={m}")

        # Both pipeline legs have now compiled on every rank this one
        # blocks on — steady state from here; the first-step grace ends.
        self._warmed = True
        return list(acc), ctx["state"]


class DistributedGPipeDataLoader:
    """Rank-aware loader: rank 0 yields ``(data, None)`` and ships targets to
    the last rank; the last rank yields ``(None, target)``; middle ranks
    yield ``(None, None)``.

    Reference: torchgpipe/distributed/gpipe.py:197-275.
    """

    def __init__(
        self,
        loader: Any,
        rank: int,
        workers: Sequence[str],
        *,
        transport: Any,
        mailbox: Any,
        num_batches: Optional[int] = None,
        recv_timeout: Optional[float] = None,
    ) -> None:
        self.loader = loader
        self.rank = rank
        self.workers = list(workers)
        self.transport = transport
        self.mailbox = mailbox
        self.recv_timeout = recv_timeout
        if loader is None and num_batches is None:
            raise ValueError("ranks without a loader need num_batches")
        self.num_batches = num_batches if num_batches is not None else len(loader)

    def __len__(self) -> int:
        return self.num_batches

    def __iter__(self) -> Iterator:
        last = len(self.workers) - 1
        if self.rank == 0:
            for step, (data, target) in enumerate(self.loader):
                if step >= self.num_batches:
                    break
                if last != 0:
                    self.transport.send(
                        self.workers[last], "target", step, target
                    )
                    yield data, None
                else:
                    yield data, target
        elif self.rank == last:
            for step in range(self.num_batches):
                target = _recv_probing_peer(
                    self.mailbox, self.transport, "target", step,
                    self.recv_timeout, 0, self.workers,
                )
                yield None, target
        else:
            for _ in range(self.num_batches):
                yield None, None
