"""Named mailboxes + pluggable transports for the multi-process pipeline.

Re-design of the reference's channel registry
(reference: torchgpipe/distributed/context.py:19-193): each worker owns a
:class:`Mailbox` of blocking channels keyed by ``(kind, index)`` — forward
activations, backward gradients, targets, and cross-rank skip tensors all
travel through the same mechanism.  Where the reference hard-codes
``torch.distributed.rpc`` one-way calls with CPU staging
(reference: torchgpipe/distributed/gpipe.py:86-96, 176-177), transport here
is pluggable:

* :class:`LocalTransport` — in-process delivery between rank objects living
  in one process (multi-device single-host runs, and the test harness; the
  reference tests mock RPC the same way,
  tests/distributed/test_distributed_gpipe.py:34-117).
* :class:`TcpTransport` — length-prefixed pickled numpy pytrees over TCP
  sockets between OS processes/hosts.  Host-staged, as the reference's RPC
  transport is.  For pod-scale TPU jobs the SPMD engine
  (:mod:`torchgpipe_tpu.spmd`) over ICI/DCN is the preferred path
  (SURVEY.md §2.3); this transport exists for capability parity with the
  reference's multi-process mode on commodity networks.

The reference's channel API (``put_forward``/``get_forward`` etc.,
distributed/context.py:96-193) maps to ``Mailbox.put/get`` with kinds
``"forward" | "backward" | "target" | ("skip", key) | ("skip_grad", key)``.
"""

from __future__ import annotations

import contextlib
import pickle
import queue
import random
import socket
import socketserver
import struct
import threading
import time
import zlib
from typing import Any, Dict, Iterator, Optional, Tuple

import jax
import numpy as np

Payload = Any
ChannelKey = Tuple[Any, int]

# Connect-retry backoff: exponential from BASE, CAPPED at CAP — the cap
# is the contract (a rank that has been retrying for a while still
# probes at least every RETRY_BACKOFF_CAP_S seconds, so a late-booting
# peer is picked up within one cap interval, never minutes).  Jitter
# (equal-jitter: half fixed, half uniform) keeps a fleet of ranks that
# all lost the same peer from re-connecting in lockstep and SYN-flooding
# its freshly restarted listener.
RETRY_BACKOFF_BASE_S = 0.5
RETRY_BACKOFF_CAP_S = 5.0


def _retry_sleep_s(attempt: int, rng: random.Random) -> float:
    """Sleep before connect retry ``attempt`` (1-based): equal-jitter
    exponential backoff, ``base * 2**(attempt-1)`` capped at
    :data:`RETRY_BACKOFF_CAP_S`, half of it jittered uniformly."""
    ceiling = min(
        RETRY_BACKOFF_CAP_S,
        RETRY_BACKOFF_BASE_S * (2.0 ** max(attempt - 1, 0)),
    )
    return ceiling / 2.0 + rng.random() * ceiling / 2.0


class PeerDiedError(TimeoutError):
    """A peer rank is confirmed dead (not merely slow).

    Refines the bare receive ``TimeoutError`` when the expected sender
    fails a liveness probe (``transport.is_alive``): unregistered from a
    :class:`LocalTransport`, or its :class:`TcpTransport` listener
    refusing connections.  Names the dead rank so the operator (or an
    external supervisor) knows WHICH worker to restart.  Subclasses
    ``TimeoutError`` so existing dead-peers-surface-as-named-timeouts
    handling keeps working — but :func:`torchgpipe_tpu.resilience.guard.
    classify_error` special-cases it FIRST as fatal (plain timeouts are
    transient): channels may hold stale messages and peers partial sends,
    so recovery is restart-and-resume from a checkpoint, not an
    in-process retry.
    """

    def __init__(self, rank: int, worker: str, detail: str = "") -> None:
        self.rank = rank
        self.worker = worker
        super().__init__(
            f"peer rank {rank} ({worker!r}) is dead"
            + (f": {detail}" if detail else "")
        )


class Mailbox:
    """Blocking channels keyed by ``(kind, micro-batch index)``.

    Reference: torchgpipe/distributed/context.py:19-26 (``TrainingContext``
    holds ``chunks`` forward + ``chunks`` backward queues + a target queue);
    here channels are created on demand, which also carries skip tensors.

    ``recorder`` (an :class:`~torchgpipe_tpu.obs.flightrec.
    FlightRecorder`, attached by the owning rank) turns every delivery
    into a ``mail_put`` flight event carrying the post-put channel depth
    — the RECEIVER-side arrival evidence the postmortem analyzer pairs
    against the sender's ``send`` event: a send with no matching arrival
    is a message lost (or hung) in transport.  ``put`` runs on sender /
    listener threads, which is why the recorder is thread-safe.
    """

    def __init__(self, name: str) -> None:
        self.name = name
        self.recorder: Optional[Any] = None
        self._channels: Dict[ChannelKey, queue.Queue] = {}
        self._lock = threading.Lock()

    def _channel(self, kind: Any, index: int) -> queue.Queue:
        key = (kind, index)
        with self._lock:
            ch = self._channels.get(key)
            if ch is None:
                ch = self._channels[key] = queue.Queue()
            return ch

    def depth(self, kind: Any, index: int) -> int:
        """Approximate queued-message count on one channel (``qsize`` —
        exact for the single-consumer engine loops)."""
        with self._lock:
            ch = self._channels.get((kind, index))
        return ch.qsize() if ch is not None else 0

    def put(self, kind: Any, index: int, payload: Payload) -> None:
        ch = self._channel(kind, index)
        ch.put(payload)
        rec = self.recorder
        if rec is not None:
            rec.record("mail_put", channel=(kind, index),
                       detail=f"depth={ch.qsize()}")

    def get(self, kind: Any, index: int, timeout: Optional[float] = None) -> Payload:
        try:
            return self._channel(kind, index).get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"worker {self.name!r}: no message on channel {(kind, index)!r} "
                f"within {timeout}s — is the peer rank alive?"
            ) from None


class LocalTransport:
    """In-process transport: a shared registry of mailboxes.

    Mirrors the reference's ``GlobalContext`` registry
    (reference: torchgpipe/distributed/context.py:28-38) without RPC.
    """

    def __init__(self) -> None:
        self._mailboxes: Dict[str, Mailbox] = {}

    def register(self, name: str) -> Mailbox:
        if name in self._mailboxes:
            raise ValueError(f"worker {name!r} already registered")
        box = Mailbox(name)
        self._mailboxes[name] = box
        return box

    def unregister(self, name: str) -> None:
        self._mailboxes.pop(name, None)

    def send(self, dst: str, kind: Any, index: int, payload: Payload) -> None:
        try:
            box = self._mailboxes[dst]
        except KeyError:
            raise KeyError(
                f"unknown worker {dst!r}; registered: {sorted(self._mailboxes)}"
            ) from None
        box.put(kind, index, payload)

    def is_alive(self, name: str) -> bool:
        """Liveness = still registered (a dead in-process rank unregisters
        via the :func:`worker` context manager's finally block)."""
        return name in self._mailboxes


def _to_host(tree: Payload) -> Payload:
    """Detach to host numpy (the reference stages through CPU the same way,
    torchgpipe/distributed/gpipe.py:176-177)."""
    return jax.tree_util.tree_map(np.asarray, tree)


class _MsgHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        data = b""
        hdr = self._recv_exact(8)
        if hdr is None:
            return
        (length,) = struct.unpack("!Q", hdr)
        data = self._recv_exact(length)
        if data is None:
            return
        kind, index, payload = pickle.loads(data)
        self.server.mailbox.put(kind, index, payload)  # type: ignore[attr-defined]

    def _recv_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf


class TcpTransport:
    """Socket transport between OS processes; one listener per worker.

    ``addresses`` maps every worker name to ``(host, port)``; this worker
    binds its own address and receives into its :class:`Mailbox`.

    ``recorder`` (optional :class:`~torchgpipe_tpu.obs.flightrec.
    FlightRecorder`) is attached to the mailbox (arrival events) and
    records the transport's OWN failure anatomy: every connect-retry
    attempt, the final connect timeout, and a send-timeout — each
    recorded BEFORE its exception is raised, so a dump from a half-dead
    pipeline shows the retry history instead of ending mid-air.

    ``registry`` (optional :class:`~torchgpipe_tpu.obs.registry.
    MetricsRegistry`) adds a ``retries_total{rank}`` counter over the
    same connect-retry attempts, so an elastic supervisor's resize
    decisions and the transport flapping that caused them cross-
    reference one incident.  Retries back off exponentially with
    equal-jitter from :data:`RETRY_BACKOFF_BASE_S`, capped at
    :data:`RETRY_BACKOFF_CAP_S` (see :func:`_retry_sleep_s`).
    """

    def __init__(
        self,
        name: str,
        addresses: Dict[str, Tuple[str, int]],
        *,
        connect_timeout: float = 120.0,
        send_timeout: Optional[float] = None,
        recorder: Optional[Any] = None,
        registry: Optional[Any] = None,
    ) -> None:
        self.name = name
        self.addresses = dict(addresses)
        self.connect_timeout = connect_timeout
        self.send_timeout = send_timeout
        self.recorder = recorder
        # Deterministic per-rank jitter stream (crc32, not hash(): str
        # hashing is salted per process, and two runs of the same rank
        # should back off identically for reproducible traces).
        self._retry_rng = random.Random(zlib.crc32(name.encode("utf-8")))
        self._c_retries = (
            registry.counter(
                "retries_total",
                help="connect-retry attempts by the retrying rank",
                labels=("rank",),
            ) if registry is not None else None
        )
        self.mailbox = Mailbox(name)
        self.mailbox.recorder = recorder
        host, port = self.addresses[name]
        self._server = socketserver.ThreadingTCPServer(
            (host, port), _MsgHandler, bind_and_activate=False
        )
        self._server.allow_reuse_address = True
        self._server.server_bind()
        self._server.server_activate()
        self._server.mailbox = self.mailbox  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def register(self, name: str) -> Mailbox:
        if name != self.name:
            raise ValueError(
                f"TcpTransport for {self.name!r} cannot register {name!r}; "
                "each process owns exactly one worker"
            )
        return self.mailbox

    def send(self, dst: str, kind: Any, index: int, payload: Payload) -> None:
        blob = pickle.dumps(
            (kind, index, _to_host(payload)), protocol=pickle.HIGHEST_PROTOCOL
        )
        host, port = self.addresses[dst]
        # Rendezvous tolerance: ranks are launched by hand in separate
        # shells (see benchmarks.distributed_accuracy), so the peer's
        # listener may not be up yet — retry refused connections until
        # connect_timeout instead of crashing the first sender.
        deadline = time.monotonic() + self.connect_timeout
        attempt = 0
        while True:
            # Clamp each attempt to the REMAINING deadline budget: a bare
            # 30s per-attempt timeout could overshoot connect_timeout by up
            # to 30s when the last attempt starts just before the deadline
            # (SYNs silently dropped, not refused).
            remaining = deadline - time.monotonic()
            per_attempt = min(30.0, max(remaining, 0.01))
            try:
                sock = socket.create_connection(
                    (host, port), timeout=per_attempt
                )
                break
            except (ConnectionRefusedError, ConnectionResetError,
                    ConnectionAbortedError, socket.timeout) as err:
                # socket.timeout (== TimeoutError) covers peers whose SYNs
                # are dropped (host still booting, lossy link) rather than
                # refused — equally transient during rendezvous.
                # Only genuinely transient rendezvous failures are retried;
                # misconfiguration (bad hostname etc.) raises immediately.
                attempt += 1
                if self._c_retries is not None:
                    self._c_retries.inc(rank=self.name)
                if self.recorder is not None:
                    self.recorder.record(
                        "connect_retry", channel=(kind, index), peer=dst,
                        detail=f"attempt={attempt} {type(err).__name__}",
                    )
                if time.monotonic() >= deadline:
                    if self.recorder is not None:
                        # Final flight event BEFORE raising: the dump of
                        # a rank that died mid-rendezvous must show the
                        # whole retry history, not end mid-air.
                        self.recorder.record(
                            "connect_timeout", channel=(kind, index),
                            peer=dst,
                            detail=f"{attempt} attempts over "
                                   f"{self.connect_timeout}s",
                        )
                    raise TimeoutError(
                        f"worker {self.name!r} could not reach {dst!r} at "
                        f"{host}:{port} within {self.connect_timeout}s — is "
                        "that rank running?"
                    ) from err
                time.sleep(_retry_sleep_s(attempt, self._retry_rng))
        with sock:
            # The connect timeout must not govern the transfer itself
            # (large activation blobs to a busy peer legitimately take
            # longer).  send_timeout (opt-in, like recv_timeout) bounds the
            # TOTAL duration of the transfer — since Python 3.5 a socket
            # timeout on sendall() is the maximum total time to send all
            # data, not a per-write budget — so a wedged peer whose listener
            # stops READING (sendall blocked on a full TCP buffer, the one
            # hang recv_timeout cannot see) and a peer draining at a trickle
            # both trip it.  Size it for your largest blob over your
            # slowest link.
            sock.settimeout(self.send_timeout)
            try:
                sock.sendall(struct.pack("!Q", len(blob)) + blob)
            except socket.timeout:
                if self.recorder is not None:
                    self.recorder.record(
                        "send_timeout", channel=(kind, index), peer=dst,
                        detail=f"{len(blob)} bytes, "
                               f"send_timeout={self.send_timeout}s",
                    )
                raise TimeoutError(
                    f"worker {self.name!r}: send of {len(blob)} bytes to "
                    f"{dst!r} did not complete within {self.send_timeout}s "
                    "— is that rank still consuming?"
                ) from None

    def is_alive(self, name: str, *, probe_timeout: float = 2.0) -> bool:
        """Liveness probe: can ``name``'s listener accept a connection?

        Used by :class:`~torchgpipe_tpu.distributed.gpipe.DistributedGPipe`
        to turn a receive timeout into a :class:`PeerDiedError` naming the
        rank when the peer is confirmed gone (connection refused/ignored),
        rather than merely busy.  A connected-then-closed probe is
        harmless to the peer: its handler reads a length header, sees EOF,
        and returns (see ``_MsgHandler.handle``).
        """
        if name == self.name:
            return True
        host, port = self.addresses[name]
        try:
            with socket.create_connection((host, port), timeout=probe_timeout):
                return True
        except OSError:
            return False

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


@contextlib.contextmanager
def worker(transport: Any, name: str) -> Iterator[Mailbox]:
    """Register a worker mailbox for the duration of a training run.

    Reference: torchgpipe/distributed/context.py:41-64 (``worker`` context
    manager / ``@distributed`` decorator).
    """
    box = transport.register(name)
    try:
        yield box
    finally:
        unregister = getattr(transport, "unregister", None)
        if unregister is not None:
            unregister(name)
