"""Multi-process (MPMD) pipeline parallelism.

Counterpart of the fork's ``torchgpipe.distributed`` package (SURVEY.md §1-L8):
per-rank pipeline stages over a pluggable transport, a named-mailbox channel
registry, and a rank-aware data loader.
"""

from torchgpipe_tpu.distributed.context import (  # noqa: F401
    LocalTransport,
    Mailbox,
    TcpTransport,
    worker,
)
from torchgpipe_tpu.distributed.gpipe import (  # noqa: F401
    DistributedGPipe,
    DistributedGPipeDataLoader,
)
