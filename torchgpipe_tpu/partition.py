"""Balance-driven partitioning of a sequential layer list into pipeline stages.

Reference: torchgpipe/gpipe.py:53-127 (``verify_module`` + ``split_module``)
including its didactic error messages, and gpipe.py:34-50
(``recommend_auto_balance``).  Device moves happen later, when the engine
places each stage's params on its device (the reference moves partitions in
``split_module``, gpipe.py:117).
"""

from __future__ import annotations

from typing import List, Sequence

from torchgpipe_tpu.layers import Layer

_RECOMMEND = (
    "If your model is still under development, its optimal balance would change\n"
    "frequently. In this case, we highly recommend "
    "torchgpipe_tpu.balance for naive automatic balancing:\n"
    "\n"
    "  from torchgpipe_tpu import GPipe\n"
    "  from torchgpipe_tpu.balance import balance_by_time\n"
    "\n"
    "  params, states, _ = sequential_init(layers, rng, in_spec)\n"
    "  balance = balance_by_time(n_stages, layers, params, states, sample)\n"
    "  model = GPipe(layers, balance, ...)\n"
)


class BalanceError(ValueError):
    """Reference: torchgpipe/gpipe.py:67-68."""


def verify_module(layers: Sequence[Layer]) -> None:
    """Validate the sequential model: a non-empty sequence of Layers with
    unique names.

    Reference: torchgpipe/gpipe.py:53-64 (Sequential? unique children? unique
    params?).  Parameter aliasing cannot happen here — params are per-layer
    pytrees produced by ``init`` — so name uniqueness is the remaining check.
    """
    if not isinstance(layers, (list, tuple)) or not layers:
        raise TypeError("model must be a non-empty list/tuple of Layers")
    names = set()
    for layer in layers:
        if not isinstance(layer, Layer):
            raise TypeError(
                f"model elements must be Layer instances, got {type(layer).__name__}"
            )
        if layer.name in names:
            raise ValueError(
                f"layer name {layer.name!r} appears twice; layer names identify "
                "partitions and must be unique (see layers.named)"
            )
        names.add(layer.name)


def split_layers(
    layers: Sequence[Layer], balance: Sequence[int]
) -> List[List[Layer]]:
    """Split layers into contiguous stages of sizes ``balance``.

    Reference: torchgpipe/gpipe.py:71-127 (``split_module``), with the same
    failure modes: balance/layer-count mismatch and non-positive entries.
    """
    balance = list(balance)
    if len(layers) != sum(balance):
        raise BalanceError(
            f"module and sum of balance have different length "
            f"(module: {len(layers)}, sum of balance: {sum(balance)})\n\n{_RECOMMEND}"
        )
    if any(x <= 0 for x in balance):
        raise BalanceError(
            f"all balance numbers must be positive integer (balance: {balance})"
        )
    stages: List[List[Layer]] = []
    i = 0
    for n in balance:
        stages.append(list(layers[i : i + n]))
        i += n
    return stages
