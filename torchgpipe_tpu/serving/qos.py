"""QoS classes on the serving scheduler: tiers, budgets, preemption.

Three mechanisms, all host-side policy over the existing machinery (the
compiled programs never see a tier — QoS changes WHICH request occupies
a slot, never a shape):

* **Latency tiers** — every request carries a ``tier``
  (``interactive`` | ``standard`` | ``batch``).  Admission is ordered
  by tier priority instead of pure FIFO: when slots are scarce an
  interactive request admits before a standard one ahead of it in the
  queue; within a tier, arrival order holds.  An all-``standard``
  workload admits exactly as before — FIFO is the degenerate case.
* **Per-tenant token budgets** — :class:`QosPolicy` accounts every
  emitted token against the request's ``tenant`` on a registry counter
  (``qos_tenant_tokens{tenant=...}``).  A tenant past its declared
  budget is DEMOTED to the batch tier — never silently dropped; its
  requests still run, they just stop outranking paying traffic.  The
  counter lives on the fleet's BASE registry, so a tenant's spend
  survives its requests migrating replicas (drain, failover,
  autoscale) — the series is keyed by tenant, not by replica.
* **Preemptible background work** — a batch-tier request yields its
  slot under pressure: when higher-priority work is queued and no slot
  is free, the engine evicts one preemptible active request through
  the SAME snapshot/teacher-force path drain uses
  (:meth:`Engine.preempt_request`) and immediately requeues it.
  Greedy decode is prefix-deterministic, so the resumed stream is
  BITWISE what an unpreempted run emits — the ``rollout-verify`` gate.

One :class:`QosPolicy` instance serves the whole fleet: build it on
the SHARED base registry and pass the same object to every engine
(a per-replica labeled view would split a tenant's spend into
per-replica series that cannot be summed back by the read path).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Optional, Tuple

from torchgpipe_tpu.obs.registry import MetricsRegistry

# Tier names in priority order: admission prefers earlier tiers.
TIERS: Tuple[str, ...] = ("interactive", "standard", "batch")
TIER_PRIORITY = {name: i for i, name in enumerate(TIERS)}


def check_tier(tier: str) -> str:
    """Validate a tier name (didactic error over a silent default)."""
    if tier not in TIER_PRIORITY:
        raise ValueError(
            f"unknown QoS tier {tier!r} — declared tiers are {TIERS}"
        )
    return tier


@dataclasses.dataclass(frozen=True)
class QosConfig:
    """Declarative QoS policy knobs.

    ``tenant_budgets`` maps tenant → token budget (emitted tokens);
    a tenant absent from the map is unbudgeted.  ``preemptible_tiers``
    names the tiers that yield slots under pressure (batch only, by
    default — interactive/standard streams are never evicted for
    priority).  ``demote_tier`` is where over-budget tenants land.
    """

    tenant_budgets: Mapping[str, int] = dataclasses.field(
        default_factory=dict
    )
    preemptible_tiers: Tuple[str, ...] = ("batch",)
    demote_tier: str = "batch"

    def __post_init__(self) -> None:
        check_tier(self.demote_tier)
        for t in self.preemptible_tiers:
            check_tier(t)
        for tenant, budget in self.tenant_budgets.items():
            if int(budget) < 1:
                raise ValueError(
                    f"tenant {tenant!r}: token budget must be >= 1, "
                    f"got {budget!r}"
                )


class QosPolicy:
    """Fleet-wide QoS accounting over one shared registry.

    Pass the SAME instance to every engine (``Engine(qos=policy)``) —
    the tenant-token counter is keyed by tenant alone, so spend follows
    the tenant across replicas and survives drain/failover migration.
    Reads never mint series (`spent` of an unseen tenant is 0.0 with no
    registry write — the phantom-series contract of PR 8).
    """

    def __init__(
        self,
        config: Optional[QosConfig] = None,
        registry: Optional[MetricsRegistry] = None,
    ) -> None:
        self.config = config or QosConfig()
        self.registry = registry or MetricsRegistry()
        self._c_tokens = self.registry.counter(
            "qos_tenant_tokens", labels=("tenant",),
            help="tokens emitted per tenant (budget accounting)",
        )
        self._c_demotions = self.registry.counter(
            "qos_demotions_total", labels=("tenant",),
            help="admissions demoted to the batch tier (over budget)",
        )
        self._c_preemptions = self.registry.counter(
            "qos_preemptions_total",
            help="preemptible requests evicted for higher-tier work",
        )

    # -------------------------------------------------------------- #
    # tenant budget accounting                                       #
    # -------------------------------------------------------------- #

    def spend(self, tenant: Optional[str], n: int = 1) -> None:
        """Charge ``n`` emitted tokens to ``tenant`` (no-op when the
        request carries no tenant)."""
        if tenant is not None and n > 0:
            self._c_tokens.inc(n, tenant=tenant)

    def spent(self, tenant: Optional[str]) -> int:
        """Tokens charged to ``tenant`` so far (0 for unseen tenants —
        a pure read, mints no series)."""
        if tenant is None:
            return 0
        return int(self._c_tokens.value(tenant=tenant))

    def budget(self, tenant: Optional[str]) -> Optional[int]:
        if tenant is None:
            return None
        b = self.config.tenant_budgets.get(tenant)
        return None if b is None else int(b)

    def over_budget(self, tenant: Optional[str]) -> bool:
        b = self.budget(tenant)
        return b is not None and self.spent(tenant) >= b

    # -------------------------------------------------------------- #
    # tier resolution                                                #
    # -------------------------------------------------------------- #

    def effective_tier(self, tier: str, tenant: Optional[str]) -> str:
        """The tier admission actually uses: the declared one, demoted
        to ``demote_tier`` while the tenant is over budget.  Demotion
        never outranks the declared tier (a batch request stays batch)."""
        check_tier(tier)
        if self.over_budget(tenant):
            demoted = self.config.demote_tier
            if TIER_PRIORITY[demoted] > TIER_PRIORITY[tier]:
                return demoted
        return tier

    def note_demotion(self, tenant: Optional[str]) -> None:
        self._c_demotions.inc(tenant="" if tenant is None else tenant)

    def note_preemption(self) -> None:
        self._c_preemptions.inc()

    def preemptible(self, tier: str) -> bool:
        return tier in self.config.preemptible_tiers


__all__ = ["QosConfig", "QosPolicy", "TIERS", "TIER_PRIORITY", "check_tier"]
