"""The serving loop: a statically bounded program set, arbitrary churn.

Steady-state contract (the whole point, and what the compile-counter
test in ``tests/test_serving.py`` pins): after warmup the engine
executes a STATICALLY BOUNDED set of compiled programs — exactly two
with a single ``prefill_chunk``, ``len(ladder) + 1`` with a prefill
bucket ladder (``prefill_chunk=(1, 2, 4, 8)``; certified by
``analysis.serving.certify_ladder``) —

* **prefill** — ``decode_slots`` at ``g = prefill_chunk`` (one program
  per ladder bucket; each step dispatches the smallest bucket covering
  its largest pending chunk): every slot's pending prompt chunk
  teacher-forced at its own frontier, masked rows no-ops; rows
  finishing their prompt sample their FIRST token from the chunk's
  last-valid-position logits (so prefill and decode share one sampling
  site semantics-wise);
* **decode** — ``decode_slots`` at ``g = 1``: one token per occupied
  slot, each at its own position.

Request arrival, completion, cancellation, drain — all of it changes
only the VALUES of ``tokens`` / ``lengths`` / ``n_valid`` / the cache
arrays, never a shape, so XLA never retraces.  The engine works from
the SAME trained pipeline params the training engines produce
(``mpmd_params_for_generation`` / ``spmd_params_for_generation`` — the
flat per-layer list), with no conversion step.

Resilience: every compiled-step dispatch retries transient failures
under :func:`torchgpipe_tpu.resilience.guard.classify_error` (bounded
backoff, :class:`~torchgpipe_tpu.resilience.guard.GuardPolicy`); a
:class:`~torchgpipe_tpu.resilience.preemption.PreemptionHandler` wired
in at build time triggers a cooperative drain between iterations —
unfinished requests snapshot (prompt + tokens emitted so far) through
:class:`~torchgpipe_tpu.resilience.checkpoint.CheckpointManager`, and
:meth:`Engine.restore_requests` resubmits them to the next incarnation,
which continues each stream exactly where it stopped (greedy decode is
prefix-deterministic, so resumed outputs equal never-preempted ones —
tested).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchgpipe_tpu.models.generation import (
    KVCache,
    QuantKVCache,
    _check_decodable,
    _sample,
    _split_params,
    decode_slots,
)
from torchgpipe_tpu.models.transformer import TransformerConfig
from torchgpipe_tpu.resilience.guard import GuardPolicy, classify_error
from torchgpipe_tpu.serving.cache_pool import CachePool
from torchgpipe_tpu.serving.metrics import ServingMetrics
from torchgpipe_tpu.serving.qos import check_tier
from torchgpipe_tpu.serving.scheduler import (
    Request,
    Scheduler,
    normalize_buckets,
)

Pytree = Any


def _start_host_copy(arr: Any) -> None:
    """Begin an ASYNC device→host copy of ``arr`` (best-effort: not
    every backend/array exposes it).  The engine calls this right after
    a step so the sampled-token transfer rides under the host-side
    bookkeeping between dispatch and the blocking ``np.asarray``."""
    start = getattr(arr, "copy_to_host_async", None)
    if start is not None:
        try:
            start()
        except Exception:  # noqa: BLE001 - a hint, never a failure
            pass


class Engine:
    """Continuous-batching inference engine over a slot-pooled KV cache.

    Example::

        flat = mpmd_params_for_generation(model, params)   # or spmd_...
        eng = Engine(cfg, flat, num_slots=4, max_len=64)
        rid = eng.submit(prompt_tokens, max_new_tokens=16, eos_id=2)
        eng.run()                       # or step() under your own loop
        tokens = eng.result(rid)        # np.int32 [n]

    ``hbm_budget_bytes`` turns on admission control: the slot cap comes
    from :func:`torchgpipe_tpu.tune.serving_max_slots`'s ``eval_shape``
    accounting of the pool (+ resident param bytes, double-buffered
    unless ``donate=True``), and the POOL ITSELF is clamped to it before
    allocation — a pool that fits is guaranteed to KEEP fitting under
    any churn, because churn only changes values.

    ``temperature=0`` (default) is greedy — the mode whose outputs are
    bit-matched against :func:`~torchgpipe_tpu.models.generation.
    generate` per-request; sampling takes ``rng`` and applies the same
    temperature/top-k/top-p filter chain ``generate`` uses, batched over
    slots.
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        params: Sequence[Pytree],
        *,
        num_slots: int,
        max_len: int,
        prefill_chunk: Any = 8,
        kv_quant: bool = False,
        cache_dtype: Optional[Any] = None,
        moe: Optional[Any] = None,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        prefix_cache: Optional[Any] = None,
        rng: Optional[jnp.ndarray] = None,
        hbm_budget_bytes: Optional[int] = None,
        overhead_bytes: int = 0,
        wave_admission: bool = False,
        metrics: Optional[ServingMetrics] = None,
        registry: Optional[Any] = None,
        reporter: Optional[Any] = None,
        recorder: Optional[Any] = None,
        clock: Callable[[], float] = time.monotonic,
        preemption: Optional[Any] = None,
        checkpoint_manager: Optional[Any] = None,
        guard_policy: Optional[GuardPolicy] = None,
        sleep: Callable[[float], None] = time.sleep,
        donate: bool = False,
        role: str = "unified",
        qos: Optional[Any] = None,
    ) -> None:
        self.cfg = cfg
        self.params = list(params)
        _split_params(cfg, self.params)  # validates the per-layer list
        # Param VERSION label (live rollout, fleet/rollout.py): every
        # response/flight event stamps the version its tokens were
        # produced under; :meth:`swap_params` bumps it in place.
        self.version = 0
        _check_decodable(cfg, max_len)
        self.moe = moe
        if moe is not None and getattr(moe, "router", "topk") == "expert_choice":
            raise ValueError(
                "expert_choice routing selects the top-C tokens PER "
                "EXPERT across the batch — at decode time the batch is "
                "one token per slot, so the experts compete over "
                "UNRELATED streams and a slot's token can be chosen by "
                "no expert (it silently emits the zero vector, "
                "corrupting that stream); serve MoE models with "
                "token-choice routing (router='topk'), which routes "
                "every token independently of its batch neighbours"
            )
        # ``prefill_chunk`` may be an int (one prefill program — the
        # classic configuration) or a LADDER of chunk sizes (e.g.
        # ``(1, 2, 4, 8)``): one program per bucket, a prefill step
        # dispatching the smallest bucket that covers its work, so short
        # prompts stop paying the max chunk's FLOPs while the program
        # count stays statically bounded at ``len(ladder) + 1``
        # (certified by ``analysis.serving.lint_serving``).
        self.prefill_buckets = normalize_buckets(prefill_chunk)
        self.prefill_chunk = self.prefill_buckets[-1]
        # Phase role (disaggregated serving, DistServe/Splitwise-style):
        # a ``prefill`` engine runs ONLY the bucket ladder and parks each
        # request at prompt completion for migration to a decode replica;
        # a ``decode`` engine runs ONLY ``decode`` + the fixed-shape
        # ``migrate_ingest`` program and receives work exclusively via
        # :meth:`ingest_migration`.  ``unified`` is the classic engine.
        # Disaggregation strictly SHRINKS each replica's program set —
        # ``analysis.serving.certify_disagg`` proves the per-role bound.
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"role must be 'unified' | 'prefill' | 'decode', "
                f"got {role!r}"
            )
        self.role = role
        if role == "decode" and prefix_cache is not None:
            raise ValueError(
                "a decode-role engine never prefills, so a prefix cache "
                "would never be consulted — attach it to the prefill "
                "pool, whose completed prompts become the donors"
            )
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        if self.temperature > 0.0 and rng is None:
            raise ValueError(
                "temperature sampling needs rng=jax.random.PRNGKey"
            )
        self._key = rng if rng is not None else jax.random.PRNGKey(0)
        self.donate = donate
        max_active: Optional[int] = None
        if hbm_budget_bytes is not None:
            from torchgpipe_tpu.tune import serving_max_slots, tree_bytes

            max_active = serving_max_slots(
                cfg, max_len, hbm_budget_bytes,
                kv_quant=kv_quant, dtype=cache_dtype,
                param_bytes=tree_bytes(self.params),
                overhead_bytes=overhead_bytes,
                donated=donate,
            )
            if max_active < 1:
                raise ValueError(
                    "admission cap is 0 slots: the cache pool does not "
                    "fit the HBM budget — shrink max_len/num_slots or "
                    "raise the budget (tune.serving_max_slots accounting)"
                )
            # The cap must bound ALLOCATED memory, not just active rows:
            # the pool's banks pin HBM at build time (BEFORE any request
            # arrives), so the pool itself is clamped to the cap here.
            num_slots = min(num_slots, max_active)
        self.pool = CachePool(
            cfg, num_slots, max_len, kv_quant=kv_quant, dtype=cache_dtype
        )
        # ``qos`` (serving.qos.QosPolicy) — ONE shared instance across a
        # fleet's engines: tier-ordered admission, per-tenant token
        # budgets, and pressure preemption of batch-tier streams.  The
        # policy object must sit on the BASE registry so a tenant's
        # spend survives its requests migrating replicas.
        self.qos = qos
        self.scheduler = Scheduler(
            self.pool, prefill_chunk=self.prefill_buckets,
            max_active=max_active, wave_admission=wave_admission,
            qos=qos,
        )
        # ``registry`` (torchgpipe_tpu.obs.MetricsRegistry) shares the
        # engine's counters + TTFT/TPOT histograms with the rest of the
        # process's telemetry; ``reporter`` (obs.StepReporter) ticks per
        # engine iteration — periodic structured log lines for the
        # serving loop (docs/observability.md).
        self.metrics = metrics or ServingMetrics(
            clock=clock, registry=registry
        )
        self.reporter = reporter
        # ``recorder`` (obs.FlightRecorder) threads a per-request span
        # record through the serving loop: submit/admit, the prefix-
        # cache copy, each prefill chunk, coalesced decode-step groups,
        # and finish/preemption — every event carrying ``rid=`` as the
        # correlation key ``obs.reqtrace.stitch_request`` rebuilds a
        # request's cross-replica span tree from.  Pure host-side ring
        # appends: trace-inert (never a traced value, never a program-
        # cache token) and zero-cost when None.
        self.recorder = recorder
        # Per-request coalescing of decode steps: one ``req_decode``
        # flight event per GROUP (flushed at finish/preempt), not one
        # per token — a 4096-event ring must hold whole requests.
        self._decode_groups: Dict[str, List[float]] = {}
        # Radix prefix-sharing KV cache (torchgpipe_tpu.fleet.
        # prefix_cache): admission consults the trie before prefilling —
        # a request whose prompt extends a cached prefix COPIES the
        # donor slot's KV rows (one fixed-shape compiled program) and
        # prefills only the remainder; completed prefills insert their
        # prompt, pinning the slot via the pool refcounts.
        self._prefix_cache = prefix_cache
        # drain hooks: called with the snapshot dict after every drain —
        # the fleet router registers here so a draining replica's
        # in-flight requests can resume elsewhere.
        self.drain_hooks: List[Callable[[Dict[str, Any]], None]] = []
        self.guard_policy = guard_policy or GuardPolicy()
        self._sleep = sleep
        self._preemption = preemption
        self._checkpoint_manager = checkpoint_manager
        self._drain_requested = False
        self._draining = False
        self._last_drain_sid: Optional[int] = None
        if preemption is not None and hasattr(preemption, "add_callback"):
            preemption.add_callback(self.request_drain)
        self._requests: Dict[str, Request] = {}
        # Requests parked at prompt completion on a prefill-role engine,
        # awaiting handoff to the decode pool: OUT of the scheduler (no
        # step touches them) but still holding their slot — the KV rows
        # ARE the migration payload, freed by :meth:`complete_migration`.
        self._migration_ready: List[Request] = []
        self._cur_tok = np.zeros((num_slots,), np.int32)
        # Device-resident slot frontiers: the compiled steps RETURN the
        # advanced lengths vector, so steady-state decode re-feeds the
        # previous step's output instead of uploading the host mirror
        # every iteration.  ``_lengths_shadow`` records what the device
        # array holds; any host-side mutation the step didn't mirror
        # (slot alloc/free on admission, eviction, drain) makes the
        # cheap per-step compare miss and triggers ONE re-upload.
        self._lengths_dev: Optional[jnp.ndarray] = None
        self._lengths_shadow: Optional[np.ndarray] = None
        self._rid_counter = 0
        # Program names: the classic single-bucket engine keeps the
        # historical "prefill" name; a ladder names each bucket's
        # program "prefill@g".  ONE source of truth for the token-buffer
        # shapes: the real steps and the lint's step_input_specs() both
        # read this, so a shape that churned with the request mix could
        # not hide.
        self._prefill_names = (
            {} if role == "decode" else {
                g: (
                    "prefill" if len(self.prefill_buckets) == 1
                    else f"prefill@{g}"
                )
                for g in self.prefill_buckets
            }
        )
        self.trace_counts = {
            name: 0 for name in self._prefill_names.values()
        }
        self._token_shapes = {
            name: (num_slots, g)
            for g, name in self._prefill_names.items()
        }
        if role != "prefill":
            self.trace_counts["decode"] = 0
            self._token_shapes["decode"] = (num_slots, 1)
        self._build_programs()

    # ------------------------------------------------------------------ #
    # compiled programs                                                  #
    # ------------------------------------------------------------------ #

    def _build_programs(self) -> None:
        cfg, moe = self.cfg, self.moe
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p
        counts = self.trace_counts

        def sample_row(logits, key):
            # [S, vocab] f32 -> [S] int32, generate's exact filter chain.
            if temperature == 0.0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), key
            key, sub = jax.random.split(key)
            return _sample(logits, sub, temperature, top_k, top_p), key

        def prefill_body_for(g, name):
            # One program per ladder bucket: the bucket size g is baked
            # into the traced shape (tokens [S, g]); the body is
            # otherwise identical across buckets.
            def prefill_body(params, cache, lengths, tokens, n_valid, key):
                counts[name] += 1
                logits, cache, _ = decode_slots(
                    cfg, params, tokens, cache, lengths, n_valid, moe=moe
                )
                last = jnp.clip(n_valid - 1, 0, g - 1)
                row_logits = jnp.take_along_axis(
                    logits, last[:, None, None], axis=1
                )[:, 0]
                tok, key = sample_row(row_logits, key)
                # Per-POSITION greedy tokens [S, g]: what the target
                # model would emit after consuming each input position.
                # Chunked prefill ignores it; speculative decoding's
                # verify pass IS this program — the grid is the
                # acceptance oracle, so speculation adds ZERO target
                # programs (fleet/speculative.py).
                grid = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                # Advance the frontiers ON DEVICE (lengths += the rows
                # each slot consumed): the next step reuses this array
                # instead of re-uploading the host mirror — the per-step
                # host→device lengths copy disappears from the
                # steady-state decode path.
                return tok, grid, cache, lengths + n_valid, key
            return prefill_body

        def decode_body(params, cache, lengths, tokens, n_valid, key):
            counts["decode"] += 1
            logits, cache, _ = decode_slots(
                cfg, params, tokens, cache, lengths, n_valid, moe=moe
            )
            tok, key = sample_row(logits[:, 0], key)
            return tok, cache, lengths + n_valid, key

        donate = (1,) if self.donate else ()
        self._prefill_fns = {
            name: jax.jit(prefill_body_for(g, name), donate_argnums=donate)
            for g, name in self._prefill_names.items()
        }
        self._decode_fn = (
            None if self.role == "prefill"
            else jax.jit(decode_body, donate_argnums=donate)
        )

        self._ingest_fn = None
        if self.role == "decode":
            counts["migrate_ingest"] = 0
            L = self.pool.max_len

            def ingest_body(cache, rows, dst, n):
                # The cross-pool twin of ``prefix_copy_body``: write a
                # migrated request's shipped KV rows (one slot's worth,
                # slot axis sliced away — see ``export_kv_rows``) into
                # rows [0, n) of slot ``dst``, every layer, K, V and
                # int8 scales.  dst/n are traced VALUES — ONE
                # fixed-shape program serves every migration, keeping
                # the decode pool's program count at exactly two.
                # Bitwise: the donor rows are what this pool's own
                # prefill of the same tokens at the same positions
                # would have written (prefill is replica-independent —
                # the disagg-verify gate), so decode resumes the greedy
                # stream unchanged.
                counts["migrate_ingest"] += 1
                row_mask = jnp.arange(L) < n          # [L]

                def put_len_axis(bank, row, axis):
                    # ``axis`` is the BANK's length axis; the shipped
                    # row lost the slot axis, so its length axis (and
                    # the mask's) sits at ``axis - 1``.
                    shape = [1] * (bank.ndim - 1)
                    shape[axis - 1] = L
                    m = row_mask.reshape(shape)
                    merged = jnp.where(m, row, bank[dst])
                    return bank.at[dst].set(merged)

                k = [put_len_axis(b, r, 1)
                     for b, r in zip(cache.k, rows["k"])]
                v = [put_len_axis(b, r, 1)
                     for b, r in zip(cache.v, rows["v"])]
                if isinstance(cache, QuantKVCache):
                    return QuantKVCache(
                        k=k, v=v,
                        k_scale=[put_len_axis(b, r, 2)
                                 for b, r in zip(cache.k_scale,
                                                 rows["k_scale"])],
                        v_scale=[put_len_axis(b, r, 2)
                                 for b, r in zip(cache.v_scale,
                                                 rows["v_scale"])],
                        length=cache.length,
                    )
                return KVCache(k=k, v=v, length=cache.length)

            self._ingest_fn = jax.jit(
                ingest_body, donate_argnums=(0,) if self.donate else ()
            )

        self._prefix_copy_fn = None
        if self._prefix_cache is not None:
            counts["prefix_copy"] = 0
            L = self.pool.max_len

            def prefix_copy_body(cache, src, dst, n):
                # Copy rows [0, n) of slot ``src`` into slot ``dst``
                # for every layer (K, V, and int8 scales).  src/dst/n
                # are traced VALUES — one fixed-shape program serves
                # every reuse, preserving the static program count.
                # Bitwise: the donor's rows are exactly what a cold
                # prefill of the same tokens at the same positions
                # writes, so a reused request's cache equals the cold
                # one bit-for-bit (the fleet-verify gate).
                counts["prefix_copy"] += 1
                row_mask = jnp.arange(L) < n          # [L]

                def copy_len_axis(bank, axis):
                    # mask shaped to broadcast along the length axis
                    shape = [1] * (bank.ndim - 1)
                    shape[axis - 1] = L
                    m = row_mask.reshape(shape)
                    merged = jnp.where(m, bank[src], bank[dst])
                    return bank.at[dst].set(merged)

                k = [copy_len_axis(b, 1) for b in cache.k]
                v = [copy_len_axis(b, 1) for b in cache.v]
                if isinstance(cache, QuantKVCache):
                    return QuantKVCache(
                        k=k, v=v,
                        k_scale=[copy_len_axis(b, 2)
                                 for b in cache.k_scale],
                        v_scale=[copy_len_axis(b, 2)
                                 for b in cache.v_scale],
                        length=cache.length,
                    )
                return KVCache(k=k, v=v, length=cache.length)

            self._prefix_copy_fn = jax.jit(
                prefix_copy_body,
                donate_argnums=(0,) if self.donate else (),
            )

    @property
    def program_count(self) -> int:
        """The statically bounded compiled-program count: one prefill
        program per ladder bucket plus the decode program (plus the one
        fixed-shape ``prefix_copy`` program when a prefix cache is
        attached) — the figure ``analysis.serving`` certifies and the
        compile-counter test confirms dynamically.  Disaggregation
        SHRINKS the bound per replica: a prefill pool drops the decode
        program, a decode pool is exactly ``decode`` +
        ``migrate_ingest``."""
        extra = 1 if self._prefix_cache is not None else 0
        if self.role == "prefill":
            return len(self.prefill_buckets) + extra
        if self.role == "decode":
            return 2
        return len(self.prefill_buckets) + 1 + extra

    def step_input_specs(self) -> Dict[str, Any]:
        """The (shape, dtype) signature of each compiled program's
        inputs — request-independent BY CONSTRUCTION (the real step
        builds its buffers from these same shapes), which is what
        :func:`torchgpipe_tpu.analysis.serving.lint_serving` certifies
        over a request-churn grid."""
        S = self.pool.num_slots
        sds = jax.ShapeDtypeStruct
        cache_spec = jax.tree_util.tree_map(
            lambda a: sds(a.shape, a.dtype), self.pool.cache
        )
        common = {
            "cache": cache_spec,
            "lengths": sds((S,), np.int32),
            "n_valid": sds((S,), np.int32),
            "key": sds(self._key.shape, self._key.dtype),
        }
        specs = {
            kind: dict(common, tokens=sds(shape, np.int32))
            for kind, shape in self._token_shapes.items()
        }
        if self._prefix_copy_fn is not None:
            scalar = sds((), np.int32)
            specs["prefix_copy"] = {
                "cache": cache_spec, "src": scalar, "dst": scalar,
                "n": scalar,
            }
        if self._ingest_fn is not None:
            scalar = sds((), np.int32)
            specs["migrate_ingest"] = {
                "cache": cache_spec, "rows": self.kv_row_specs(),
                "dst": scalar, "n": scalar,
            }
        return specs

    def kv_row_specs(self) -> Dict[str, Any]:
        """The (shape, dtype) signature of ONE slot's migration payload:
        per-layer KV rows (+ int8 scale rows) with the slot axis sliced
        away — exactly what :meth:`export_kv_rows` produces and the
        ``migrate_ingest`` program consumes.  Cross-pool compatibility
        in a disaggregated fleet is certified by comparing these specs
        between the prefill and decode engines
        (``analysis.serving.certify_disagg``)."""
        sds = jax.ShapeDtypeStruct
        c = self.pool.cache
        rows: Dict[str, Any] = {
            "k": [sds(b.shape[1:], b.dtype) for b in c.k],
            "v": [sds(b.shape[1:], b.dtype) for b in c.v],
        }
        if isinstance(c, QuantKVCache):
            rows["k_scale"] = [sds(b.shape[1:], b.dtype) for b in c.k_scale]
            rows["v_scale"] = [sds(b.shape[1:], b.dtype) for b in c.v_scale]
        return rows

    def _token_buffer(self, kind: str) -> np.ndarray:
        return np.zeros(self._token_shapes[kind], np.int32)

    def _lengths_for_step(self) -> jnp.ndarray:
        """The frontier vector for the next compiled step: the previous
        step's device output when the host mirror still matches its
        shadow, else one fresh upload (``pool.lengths_device()``'s copy
        semantics).  Steady-state decode — no admissions, no evictions —
        pays ZERO host→device lengths transfers."""
        if self._lengths_dev is None or not np.array_equal(
            self.pool.lengths, self._lengths_shadow
        ):
            self._lengths_dev = self.pool.lengths_device()
            self._lengths_shadow = np.array(self.pool.lengths, copy=True)
        return self._lengths_dev

    def _commit_lengths(self, lengths_dev: jnp.ndarray,
                        n_valid: np.ndarray) -> None:
        """Adopt the step's advanced device frontiers and mirror the
        advance into the shadow (the engine's own per-row host
        bookkeeping applies the same ``+= n_valid`` to
        ``pool.lengths``, so the compare in :meth:`_lengths_for_step`
        keeps matching until something OTHER than a step mutates it)."""
        self._lengths_dev = lengths_dev
        self._lengths_shadow = self._lengths_shadow + n_valid

    def _dispatch(self, fn: Callable[..., Tuple], *args: Any) -> Tuple:
        """Run a compiled step under the transient-retry policy (the
        serving twin of StepGuard's retry half; inputs are not donated
        unless ``donate=True``, in which case retry is impossible and
        transient errors re-raise immediately)."""
        attempt = 0
        while True:
            try:
                # jit dispatch is ASYNC: a device-execution failure
                # surfaces on materialization, so block here — letting
                # it escape to the caller's host fetch would skip the
                # retry AND commit the failed step's arrays to the pool
                # first.  Free in practice: the engine host-fetches the
                # step's tokens immediately anyway.
                return jax.block_until_ready(fn(*args))
            except Exception as err:  # noqa: BLE001 — classified below
                if (
                    self.donate
                    or classify_error(err) != "transient"
                    or attempt >= self.guard_policy.max_retries
                ):
                    raise
                delay = self.guard_policy.backoff(attempt)
                attempt += 1
                self.metrics.retries += 1
                self._sleep(delay)

    @property
    def compile_stats(self) -> Dict[str, int]:
        """Times each program's python body was TRACED — the zero-retrace
        contract is ``{'prefill': 1, 'decode': 1}`` after warmup."""
        return dict(self.trace_counts)

    # ------------------------------------------------------------------ #
    # live param rollout (fleet/rollout.py)                              #
    # ------------------------------------------------------------------ #

    def swap_params(self, params: Sequence[Pytree], version: int) -> None:
        """In-place param refresh: serve a NEW weight version with zero
        rebuild.  The compiled programs take ``params`` as a traced
        ARGUMENT, so replacing the list with one whose every leaf keeps
        its (shape, dtype) signature triggers ZERO retraces — the KV
        pool, the program cache and every in-flight request are
        untouched, and subsequent steps simply read the new weights
        (``analysis.serving.certify_swap`` is the static twin of this
        check).  A swap that changes any leaf signature would recompile
        every program mid-serve and is REFUSED — cold-start a fresh
        engine for a re-shaped model.

        Call only on a drained/idle replica (the rollout controller
        drains first): swapping under live decode would splice two
        versions into one stream.  After the swap the engine's streams
        are bitwise what a fresh engine cold-started on ``params``
        produces — the ``rollout-verify`` gate.
        """
        new = list(params)
        _split_params(self.cfg, new)    # validates the per-layer list

        def sig(tree: Any) -> List[Tuple[Tuple[int, ...], str]]:
            return [
                (tuple(a.shape), str(a.dtype))
                for a in jax.tree_util.tree_leaves(tree)
            ]

        if sig(new) != sig(self.params):
            raise ValueError(
                "swap_params: the published params change a leaf "
                "(shape, dtype) signature — an in-place swap would "
                "retrace every compiled program mid-serve, so a "
                "new-version compile is refused; cold-start a fresh "
                "Engine for a re-shaped model "
                "(analysis.serving.certify_swap names the mismatch)"
            )
        self.params = new
        self.version = int(version)
        if self.recorder is not None:
            self.recorder.record(
                "param_swap", detail=f"version={self.version}"
            )

    # ------------------------------------------------------------------ #
    # request-scoped flight recording                                    #
    # ------------------------------------------------------------------ #

    def _rec(self, kind: str, rid: str, *, dur: Optional[float] = None,
             detail: str = "") -> None:
        """One rid-keyed flight event (no-op without a recorder)."""
        if self.recorder is not None:
            self.recorder.record(kind, rid=rid, dur=dur, detail=detail)

    def _rec_clock(self) -> float:
        """The recorder's clock (0.0 without one — callers only use the
        value when a recorder exists, so durs stay self-consistent with
        the recorder's own event timestamps)."""
        return self.recorder.clock() if self.recorder is not None else 0.0

    def _flush_decode_group(self, rid: str) -> None:
        """Emit the coalesced decode-step span for ``rid`` (if any):
        dur spans first-step start to last-step end, detail carries the
        step count."""
        group = self._decode_groups.pop(rid, None)
        if group is None or self.recorder is None:
            return
        t0, t1, steps = group
        self._rec("req_decode", rid, dur=max(t1 - t0, 0.0),
                  detail=f"steps={int(steps)}")

    # ------------------------------------------------------------------ #
    # request API                                                        #
    # ------------------------------------------------------------------ #

    def submit(
        self,
        prompt: Any,
        max_new_tokens: int,
        *,
        rid: Optional[str] = None,
        eos_id: Optional[int] = None,
        on_token: Optional[Callable[[str, int], None]] = None,
        emitted_prefix: Sequence[int] = (),
        tier: str = "standard",
        tenant: Optional[str] = None,
    ) -> str:
        """Queue a request; returns its id.  Admission happens between
        engine iterations (a free slot + the admission cap permitting).
        """
        if self.role == "decode":
            raise ValueError(
                "decode-role engine: work arrives via ingest_migration() "
                "from a prefill replica, never submit() — route "
                "admissions to the prefill pool"
            )
        check_tier(tier)     # before any registration (no phantom state)
        if rid is None:
            self._rid_counter += 1
            rid = f"r{self._rid_counter}"
        self._check_rid_free(rid)
        req = Request(
            rid=rid,
            prompt=np.asarray(prompt, np.int32).reshape(-1),
            max_new_tokens=int(max_new_tokens),
            eos_id=eos_id,
            on_token=on_token,
            emitted_prefix=list(emitted_prefix),
            tier=tier,
            tenant=tenant,
        )
        self.scheduler.submit(req)   # validates before registration
        self._requests[rid] = req
        self.metrics.arrived(rid)
        # Recorded only AFTER validation accepted the request — a
        # rejected submit must leave no phantom span behind (the same
        # contract the router keeps for its records).
        phase = "" if self.role == "unified" else f" phase={self.role}"
        tenant_tag = "" if tenant is None else f" tenant={tenant}"
        self._rec(
            "req_submit", rid,
            detail=(
                f"prompt={req.prompt_len} new={req.max_new_tokens} "
                f"queued={self.scheduler.queue_depth}"
                f" tier={tier}{tenant_tag}"
                f" version={self.version}{phase}"
            ),
        )
        return rid

    def _check_rid_free(self, rid: str) -> None:
        """A rid may legitimately RETURN to an engine that served it
        before — failover and drain/unpark cycles bounce unfinished
        requests between replicas, and in a disaggregated fleet every
        resumption re-prefills before re-migrating — but only once its
        prior incarnation here is inert.  A still-live duplicate is a
        real bug and stays an error."""
        old = self._requests.get(rid)
        if old is not None and old.status in (
            "queued", "active", "migrating", "finished"
        ):
            raise ValueError(f"duplicate request id {rid!r}")

    def cancel(self, rid: str) -> bool:
        ok = self.scheduler.cancel(rid)
        if ok:
            self.metrics.finished(rid, status="cancelled")
            self._flush_decode_group(rid)
            self._rec("req_finish", rid, detail="status=cancelled")
        return ok

    def result(self, rid: str) -> np.ndarray:
        """All tokens request ``rid`` has produced so far (across a
        drain/resume), as ``np.int32 [n]``."""
        return np.asarray(self._requests[rid].tokens(), np.int32)

    def status(self, rid: str) -> str:
        return self._requests[rid].status

    # ------------------------------------------------------------------ #
    # the loop                                                           #
    # ------------------------------------------------------------------ #

    def step(self) -> bool:
        """ONE engine iteration: admit, pick a phase, run its compiled
        program, emit/evict.  Returns False when idle (nothing ran)."""
        if not self._draining:
            if self.qos is not None:
                self._preempt_for_pressure()
            if (
                self._prefix_cache is not None
                and self.scheduler.queue
                and self.pool.num_free == 0
            ):
                # Admission pressure: evict idle prefix entries (their
                # pins are the only remaining references) so queued
                # requests beat cached prefixes to slots.
                self._prefix_cache.reclaim(
                    self.pool, len(self.scheduler.queue)
                )
            for req in self.scheduler.admit():
                self.metrics.admitted(req.rid)
                self._on_admit(req)
        action = self.scheduler.next_action()
        if action is None:
            return False
        if action == "prefill":
            self._run_prefill()
        else:
            self._run_decode()
        if self.reporter is not None:
            self.reporter.step()
        return True

    def _preempt_for_pressure(self) -> None:
        """QoS pressure valve (runs before admission): when queued work
        OUTRANKS an active preemptible stream and admission is blocked
        (no free slot, or the cap is reached), evict ONE preemptible
        active request through the same teacher-forced snapshot path
        drain uses and requeue it here — it resumes bitwise (greedy
        decode is prefix-deterministic) once pressure clears.  At most
        one eviction per engine iteration; interactive/standard streams
        are never preempted."""
        sched = self.scheduler
        if not sched.queue:
            return
        if sched.pool.num_free > 0 and len(sched.active) < sched.max_active:
            return      # admission can proceed — nothing to yield
        from torchgpipe_tpu.serving.qos import TIER_PRIORITY

        want = min(
            TIER_PRIORITY[self.qos.effective_tier(r.tier, r.tenant)]
            for r in sched.queue
        )
        victims = [
            r for r in sched.active.values()
            if self.qos.preemptible(r.tier)
            and TIER_PRIORITY[r.tier] > want
        ]
        if not victims:
            return
        # Most recently admitted among the worst-priority preemptibles:
        # deterministic, and the stream with the least progress to redo.
        worst = max(TIER_PRIORITY[r.tier] for r in victims)
        victim = [r for r in victims if TIER_PRIORITY[r.tier] == worst][-1]
        kwargs = self.preempt_request(victim.rid)
        self.qos.note_preemption()
        self.submit(**kwargs)

    def preempt_request(self, rid: str) -> Dict[str, Any]:
        """Evict one ACTIVE request NOW (its slot frees immediately) and
        return the ``submit()`` kwargs that resume it: prompt extended
        by the tokens already emitted (teacher-forced), budget shrunk,
        ``emitted_prefix`` extended — exactly the drain/restore schema,
        per-request.  Greedy decode is prefix-deterministic, so the
        resumed stream is bitwise the unpreempted one."""
        req = self.scheduler.active.get(rid)
        if req is None:
            raise ValueError(
                f"request {rid!r} is not active — nothing to preempt"
            )
        generated = list(req.generated)
        kwargs: Dict[str, Any] = {
            "rid": req.rid,
            "prompt": np.concatenate([
                np.asarray(req.prompt, np.int32),
                np.asarray(generated, np.int32),
            ]) if generated else np.asarray(req.prompt, np.int32),
            "max_new_tokens": req.max_new_tokens - len(generated),
            "eos_id": req.eos_id,
            "on_token": req.on_token,
            "emitted_prefix": list(req.emitted_prefix) + generated,
            "tier": req.tier,
            "tenant": req.tenant,
        }
        req.status = "preempted"
        self.scheduler.release(req)
        self.metrics.finished(rid, status="preempted")
        self._flush_decode_group(rid)
        self._rec(
            "req_preempt", rid,
            detail=f"qos tier={req.tier} emitted={len(generated)}",
        )
        return kwargs

    def _on_admit(self, req: Request) -> None:
        """Per-admission hook: prefix-cache consult here; subclasses
        extend (``fleet.SpeculativeEngine`` resets the recycled slot's
        draft frontier)."""
        if self.recorder is not None:
            times = self.metrics.requests.get(req.rid)
            wait = times.queue_wait if times is not None else None
            self._rec("req_admit", req.rid, dur=wait,
                      detail=f"slot={req.slot}")
        if self._prefix_cache is not None:
            self._apply_prefix_reuse(req)

    def _apply_prefix_reuse(self, req: Request) -> None:
        """Admission-time trie consult: when the prompt extends a cached
        prefix, copy the donor slot's KV rows into the request's slot
        (one fixed-shape compiled dispatch, bitwise-equal to cold
        prefill of the same tokens) and mark the prefix absorbed.  At
        most ``prompt_len - 1`` tokens reuse — the LAST prompt token
        always prefills, producing the first-token logits."""
        pc = self._prefix_cache
        m, donor = pc.match(req.prompt, limit=req.prompt_len - 1)
        if m <= 0 or donor is None:
            return
        assert req.slot is not None
        t0 = self._rec_clock()
        new_cache = self._dispatch(
            self._prefix_copy_fn, self.pool.cache,
            jnp.int32(donor), jnp.int32(req.slot), jnp.int32(m),
        )
        self.pool.cache = new_cache
        self.pool.lengths[req.slot] = m      # shadow miss -> re-upload
        req.prefilled = m
        self.metrics.prefix_hit(m)
        self._rec("req_prefix_copy", req.rid,
                  dur=max(self._rec_clock() - t0, 0.0),
                  detail=f"reused={m} donor_slot={donor}")

    def _run_prefill(self) -> None:
        reqs = self.scheduler.prefill_pending()
        # Ladder admission: the smallest bucket covering this step's
        # largest pending chunk — short prompts dispatch a small program
        # instead of paying the max chunk's FLOPs.
        g = self.scheduler.prefill_bucket()
        name = self._prefill_names[g]
        tokens = self._token_buffer(name)
        n_valid = np.zeros((self.pool.num_slots,), np.int32)
        takes: List[Tuple[Request, int]] = []
        for r in reqs:
            take = min(g, r.prompt_len - r.prefilled)
            tokens[r.slot, :take] = r.prompt[r.prefilled:r.prefilled + take]
            n_valid[r.slot] = take
            takes.append((r, take))
        t0 = self._rec_clock()
        tok, _grid, cache, lengths_dev, key = self._dispatch(
            self._prefill_fns[name], self.params, self.pool.cache,
            self._lengths_for_step(), jnp.asarray(tokens),
            jnp.asarray(n_valid), self._key,
        )
        self.pool.cache = cache
        self._key = key
        if self.recorder is not None:
            dur = max(self._rec_clock() - t0, 0.0)
            for r, take in takes:
                self._rec("req_prefill", r.rid, dur=dur,
                          detail=f"g={g} take={take}")
        # Start the device→host token copy NOW; the per-row bookkeeping
        # below runs while it is in flight (copy_to_host_async is a hint
        # — np.asarray below is the one materialization point).
        _start_host_copy(tok)
        # Subclass hook: speculative decoding mirrors every prefill
        # chunk into its draft model's cache (same bucket, same buffer)
        # so draft and target stay frontier-aligned.
        self._after_prefill_dispatch(g, tokens, n_valid)
        self._commit_lengths(lengths_dev, n_valid)
        self.metrics.step("prefill", len(reqs), self.pool.num_slots)
        tok_host: Optional[np.ndarray] = None
        for r, take in takes:
            self.pool.lengths[r.slot] += take
            r.prefilled += take
            if r.prefill_done:
                if self._prefix_cache is not None:
                    # The slot now holds the full prompt's KV: it
                    # becomes a donor (the insert pins it via the pool
                    # refcounts, so recycling waits for eviction).
                    self._prefix_cache.insert(
                        r.prompt, r.slot, self.pool
                    )
                if tok_host is None:
                    tok_host = np.asarray(tok)  # ONE host fetch per step
                self._emit(r, int(tok_host[r.slot]))

    def _after_prefill_dispatch(
        self, g: int, tokens: np.ndarray, n_valid: np.ndarray
    ) -> None:
        """No-op hook; ``fleet.SpeculativeEngine`` overrides it to
        teacher-force the same prompt chunk into the draft cache."""

    def _run_decode(self) -> None:
        reqs = self.scheduler.decode_ready()
        tokens = self._token_buffer("decode")
        n_valid = np.zeros((self.pool.num_slots,), np.int32)
        for r in reqs:
            tokens[r.slot, 0] = self._cur_tok[r.slot]
            n_valid[r.slot] = 1
        t0 = self._rec_clock()
        tok, cache, lengths_dev, key = self._dispatch(
            self._decode_fn, self.params, self.pool.cache,
            self._lengths_for_step(), jnp.asarray(tokens),
            jnp.asarray(n_valid), self._key,
        )
        self.pool.cache = cache
        self._key = key
        _start_host_copy(tok)           # overlap D2H with the bookkeeping
        self._commit_lengths(lengths_dev, n_valid)
        self.metrics.step("decode", len(reqs), self.pool.num_slots)
        if self.recorder is not None:
            t1 = self._rec_clock()
            for r in reqs:
                group = self._decode_groups.get(r.rid)
                if group is None:
                    self._decode_groups[r.rid] = [t0, t1, 1.0]
                else:
                    group[1] = t1
                    group[2] += 1.0
        tok_host = np.asarray(tok)      # the ONE host fetch per step
        for r in reqs:
            self.pool.lengths[r.slot] += 1
            self._emit(r, int(tok_host[r.slot]))

    def _emit(self, req: Request, token: int) -> None:
        """Stream one token; per-row termination FREES THE SLOT NOW —
        the iteration-level eviction continuous batching is made of."""
        req.generated.append(token)
        self.metrics.token(req.rid)
        if self.qos is not None:
            self.qos.spend(req.tenant, 1)
        if req.on_token is not None:
            req.on_token(req.rid, token)
        done = (
            (req.eos_id is not None and token == req.eos_id)
            or req.remaining_new <= 0
        )
        if done:
            req.status = "finished"
            self.scheduler.release(req)
            self.metrics.finished(req.rid)
            self._flush_decode_group(req.rid)
            self._rec(
                "req_finish", req.rid,
                detail=(
                    f"status=finished tokens={len(req.tokens())} "
                    f"version={self.version}"
                ),
            )
        elif self.role == "prefill":
            # Prompt complete, stream live: the decode phase belongs to
            # the decode pool.  Park the request OUT of the scheduler
            # (no step may touch it again here) with its slot still
            # held — the KV rows are the migration payload, released by
            # complete_migration() once a decode replica has ingested
            # them.  Requests finishing on their first token never park.
            req.status = "migrating"
            self.scheduler.active.pop(req.rid, None)
            self._migration_ready.append(req)
            self._flush_decode_group(req.rid)
        else:
            self._cur_tok[req.slot] = token

    # ------------------------------------------------------------------ #
    # KV migration (disaggregated serving)                               #
    # ------------------------------------------------------------------ #

    @property
    def migration_pending(self) -> bool:
        """Requests parked at prompt completion, awaiting handoff to a
        decode replica (prefill role only)."""
        return bool(self._migration_ready)

    def take_migration_ready(self) -> List[Request]:
        """Pop the parked requests (the router hands each to
        :func:`torchgpipe_tpu.fleet.migration.migrate`); append back to
        ``_migration_ready`` to re-park one the decode pool cannot take
        yet."""
        out = self._migration_ready
        self._migration_ready = []
        return out

    def export_kv_rows(self, req: Request) -> Dict[str, Any]:
        """One slot's migration payload: per-layer KV rows (+ int8
        scale rows) with the slot axis sliced away.  Device-array views
        — zero-copy for an in-process handoff; ``np.asarray`` each leaf
        to stage the snapshot across a process boundary (the
        drain-schema flavor).  Shapes/dtypes match
        :meth:`kv_row_specs`."""
        if req.slot is None:
            raise ValueError(
                f"request {req.rid!r} holds no slot — nothing to export"
            )
        slot = req.slot
        c = self.pool.cache
        rows: Dict[str, Any] = {
            "k": [b[slot] for b in c.k],
            "v": [b[slot] for b in c.v],
        }
        if isinstance(c, QuantKVCache):
            rows["k_scale"] = [b[slot] for b in c.k_scale]
            rows["v_scale"] = [b[slot] for b in c.v_scale]
        return rows

    def complete_migration(self, req: Request) -> None:
        """Donor-side epilogue: the decode replica has ingested the KV
        rows — free the slot (a prefix-cache donor pin, if any, keeps
        the rows alive for future hits) and close the books here."""
        req.status = "migrated"
        self.scheduler.release(req)
        self.metrics.migrated_out(req.rid)
        self._rec(
            "req_handoff", req.rid,
            detail=f"phase={self.role} emitted={len(req.generated)}",
        )

    def ingest_migration(
        self,
        *,
        rid: str,
        prompt: Any,
        max_new_tokens: int,
        rows: Dict[str, Any],
        last_token: int,
        eos_id: Optional[int] = None,
        on_token: Optional[Callable[[str, int], None]] = None,
        emitted_prefix: Sequence[int] = (),
        tier: str = "standard",
        tenant: Optional[str] = None,
    ) -> str:
        """Receive a mid-stream request from a prefill replica: allocate
        a slot, write the shipped KV ``rows`` through the fixed-shape
        ``migrate_ingest`` program, and register the request exactly as
        a unified engine would hold it after emitting its first token
        (``last_token``) — so the decode stream continues bitwise.

        Deliberately BYPASSES admission: no queue, no prefix-cache
        consult (a migrated request whose prompt was a prefix hit on
        the donor must not re-pin donor slots here), no re-fire of
        ``on_token`` for the carried token (the donor already streamed
        it).  ``max_new_tokens`` is the request's ORIGINAL budget; the
        carried token counts against it.  Raises ``RuntimeError`` when
        the pool has no free slot — the router re-parks and retries
        once decode slots free up."""
        if self.role != "decode":
            raise ValueError(
                "ingest_migration is the decode pool's entry point — "
                f"this engine's role is {self.role!r}"
            )
        self._check_rid_free(rid)
        check_tier(tier)
        req = Request(
            rid=rid,
            prompt=np.asarray(prompt, np.int32).reshape(-1),
            max_new_tokens=int(max_new_tokens),
            eos_id=eos_id,
            on_token=on_token,
            emitted_prefix=list(emitted_prefix),
            tier=tier,
            tenant=tenant,
        )
        if req.prompt_len + req.max_new_tokens > self.pool.max_len:
            raise ValueError(
                f"request {rid!r}: prompt ({req.prompt_len}) + "
                f"max_new_tokens ({req.max_new_tokens}) exceeds this "
                f"pool's max_len={self.pool.max_len} — a disaggregated "
                "fleet needs equal max_len across roles"
            )
        slot = self.pool.alloc(rid)
        if slot is None:
            raise RuntimeError(
                "decode pool full: no free slot for migrated request "
                f"{rid!r} — retry when a stream finishes"
            )
        rows_dev = jax.tree_util.tree_map(jnp.asarray, rows)
        t0 = self._rec_clock()
        try:
            new_cache = self._dispatch(
                self._ingest_fn, self.pool.cache, rows_dev,
                jnp.int32(slot), jnp.int32(req.prompt_len),
            )
        except Exception:
            # Register NOTHING on failure: the router's failover of a
            # replica that broke mid-ingest must find it clean — the
            # request is still parked on the donor, slot and all.
            self.pool.free(slot)
            raise
        req.slot = slot
        req.status = "active"
        req.prefilled = req.prompt_len
        req.generated = [int(last_token)]   # emitted on the donor
        self._requests[rid] = req
        self.scheduler.active[rid] = req
        self.metrics.arrived(rid)
        self.metrics.ingested(rid)
        self.pool.cache = new_cache
        self.pool.lengths[slot] = req.prompt_len  # shadow miss → upload
        self._cur_tok[slot] = int(last_token)
        self._rec(
            "req_ingest", rid,
            dur=max(self._rec_clock() - t0, 0.0),
            detail=(
                f"phase=decode rows={req.prompt_len} slot={slot} "
                f"emitted={len(req.generated)}"
            ),
        )
        return rid

    def run(self, max_steps: Optional[int] = None) -> str:
        """Iterate until idle, preempted, or ``max_steps``.  Returns
        ``'idle'`` | ``'preempted'`` | ``'budget'``."""
        steps = 0
        while not self.scheduler.idle:
            if self._preempted():
                self.drain()
                return "preempted"
            if not self.step():
                break
            steps += 1
            if max_steps is not None and steps >= max_steps:
                return "budget"
        return "idle"

    # ------------------------------------------------------------------ #
    # drain / resume (resilience)                                        #
    # ------------------------------------------------------------------ #

    def request_drain(self) -> None:
        """Ask the engine to drain at the next iteration boundary (safe
        from a PreemptionHandler callback or another thread)."""
        self._drain_requested = True

    def resume_serving(self) -> None:
        """Re-open a drained engine for admissions.  A drain empties the
        scheduler and frees every slot but leaves the engine refusing
        new work; the fleet router calls this when it re-admits a
        recovered (SLO-degraded) replica into rotation — the compiled
        programs and pool are unchanged, so serving resumes without a
        rebuild."""
        self._draining = False
        self._drain_requested = False

    def _preempted(self) -> bool:
        if self._drain_requested:
            return True
        h = self._preemption
        return bool(h is not None and getattr(h, "preempted", False))

    def drain(self, step_id: Optional[int] = None) -> Dict[str, Any]:
        """Cooperative drain: stop admitting, snapshot every unfinished
        request (original prompt + tokens emitted so far), release all
        slots, and — when a CheckpointManager is wired — persist the
        snapshot.  Returns the snapshot dict."""
        self._draining = True
        # Migration-parked requests (prefill role) are in-flight too:
        # they left the scheduler but not the replica — a drain must
        # snapshot them or a dying prefill replica would strand every
        # prompt caught between completion and handoff.
        unfinished = (
            list(self.scheduler.queue)
            + list(self.scheduler.active.values())
            + list(self._migration_ready)
        )
        tree: Dict[str, Dict[str, np.ndarray]] = {}
        meta: Dict[str, Dict[str, Any]] = {}
        for r in unfinished:
            tree[r.rid] = {
                "prompt": np.asarray(r.prompt, np.int32),
                "generated": np.asarray(r.generated, np.int32),
            }
            meta[r.rid] = {
                "max_new_tokens": r.max_new_tokens,
                "eos_id": r.eos_id,
                "emitted_prefix": list(r.emitted_prefix),
                "prompt_len": r.prompt_len,
                "generated_len": len(r.generated),
                "tier": r.tier,
                "tenant": r.tenant,
            }
        if self.recorder is not None:
            for r in unfinished:
                self._flush_decode_group(r.rid)
                self._rec("req_preempt", r.rid,
                          detail=f"drain emitted={len(r.generated)}")
            self.recorder.record(
                "drain", detail=f"{len(unfinished)} in-flight"
            )
        for r in list(self.scheduler.active.values()):
            r.status = "preempted"
            self.scheduler.release(r)
        for r in list(self.scheduler.queue):
            r.status = "preempted"
        self.scheduler.queue.clear()
        for r in self._migration_ready:
            r.status = "preempted"
            self.scheduler.release(r)   # frees the held slot
        self._migration_ready.clear()
        self.metrics.drained(len(unfinished))
        for rid in meta:
            self.metrics.finished(rid, status="preempted")
        # Persist only when there is something to restore, and never at a
        # step id already used by an earlier drain: CheckpointManager.save
        # REPLACES an existing step_<n> snapshot, so an empty (or repeated)
        # drain at the same id would silently destroy the one that holds
        # the in-flight requests.
        if self._checkpoint_manager is not None and meta:
            sid = (
                step_id if step_id is not None
                else self.metrics.engine_steps
            )
            if self._last_drain_sid is not None:
                sid = max(sid, self._last_drain_sid + 1)
            self._checkpoint_manager.save(
                sid, tree, metadata={"requests": meta}
            )
            self._last_drain_sid = sid
        self._drain_requested = False
        snapshot = {"tree": tree, "requests": meta}
        for hook in list(self.drain_hooks):
            hook(snapshot)
        return snapshot

    @staticmethod
    def restore_requests(source: Any) -> List[Dict[str, Any]]:
        """Rebuild submit() kwargs for every request a drain snapshot
        holds — from a CheckpointManager or a :meth:`drain` return.

        Each entry resubmits with the prompt EXTENDED by the tokens
        already emitted (teacher-forced on resume) and the budget shrunk
        accordingly; greedy decode being prefix-deterministic, the
        resumed stream continues exactly where the drained one stopped.
        """
        if isinstance(source, dict):
            meta = source["requests"]
            tree = source["tree"]
        else:
            snap = source.restore_latest()
            if snap is None:
                return []
            meta = snap.metadata["requests"]
            template = {
                rid: {
                    "prompt": np.zeros((m["prompt_len"],), np.int32),
                    "generated": np.zeros((m["generated_len"],), np.int32),
                }
                for rid, m in meta.items()
            }
            tree = source.restore_step(snap.step, template).tree
        out: List[Dict[str, Any]] = []
        for rid, m in meta.items():
            prompt = np.asarray(tree[rid]["prompt"], np.int32)
            generated = np.asarray(tree[rid]["generated"], np.int32)
            out.append({
                "rid": rid,
                "prompt": np.concatenate([prompt, generated]),
                "max_new_tokens": int(m["max_new_tokens"]) - generated.size,
                "eos_id": m["eos_id"],
                "emitted_prefix": (
                    list(m["emitted_prefix"]) + generated.tolist()
                ),
                # QoS identity rides the snapshot (absent in pre-QoS
                # snapshots — the defaults keep them restorable).
                "tier": m.get("tier", "standard"),
                "tenant": m.get("tenant"),
            })
        return out


__all__ = ["Engine"]
