"""Continuous-batching inference serving over the pipelined decode path.

The training side of this framework ends at trained per-stage params;
this package turns them into a server: Orca-style iteration-level
batching (arXiv: OSDI '22) over a slot-pooled KV cache (the
PagedAttention idea, arXiv:2309.06180, specialised to one fixed-size
page per request — the shape-static variant TPU serving requires), with
exactly TWO compiled programs in steady state regardless of request
churn.

    from torchgpipe_tpu import serving
    eng = serving.Engine(cfg, flat_params, num_slots=8, max_len=256)
    rid = eng.submit(prompt, max_new_tokens=64, eos_id=2,
                     on_token=lambda rid, t: print(t))
    eng.run()
    tokens = eng.result(rid)

Modules: :mod:`~torchgpipe_tpu.serving.cache_pool` (slot banks +
free-list), :mod:`~torchgpipe_tpu.serving.scheduler` (admission /
chunked-prefill interleave / eviction),
:mod:`~torchgpipe_tpu.serving.engine` (the two-program loop, streaming,
drain/resume), :mod:`~torchgpipe_tpu.serving.metrics` (TTFT / TPOT /
occupancy / throughput).  Full story: ``docs/serving.md``.
"""

from __future__ import annotations

from torchgpipe_tpu.serving.cache_pool import CachePool
from torchgpipe_tpu.serving.engine import Engine
from torchgpipe_tpu.serving.metrics import RequestTimes, ServingMetrics
from torchgpipe_tpu.serving.qos import QosConfig, QosPolicy
from torchgpipe_tpu.serving.scheduler import Request, Scheduler

__all__ = [
    "CachePool",
    "Engine",
    "QosConfig",
    "QosPolicy",
    "Request",
    "RequestTimes",
    "Scheduler",
    "ServingMetrics",
]
