"""Serving observability: per-request latency and engine-level counters.

The two layers a decode server is judged by (Orca, OSDI '22; vLLM,
arXiv:2309.06180):

* **Per-request latency** — :class:`RequestTimes` tracks arrival →
  admission → first token → finish, from which the standard quantities
  derive: queue wait (admitted − arrival), TTFT (first token − arrival),
  TPOT (decode time per subsequent token).
* **Engine throughput** — per-iteration counters: how many compiled
  steps of each kind ran, how many slot-steps were occupied vs idle
  (occupancy is THE continuous-batching win: recycled slots keep the
  batch dim full), tokens emitted, transient retries, drains and the
  requests they preempted.

Re-based on :class:`torchgpipe_tpu.obs.MetricsRegistry`: every counter
is a registry series and TTFT/TPOT/queue-wait stream into registry
histograms, so ``snapshot()`` now also reports **p50/p95/p99 TTFT and
TPOT** and the whole set exports as JSONL or Prometheus text through
``metrics.registry``.  The public API is unchanged — attributes read
and assign as plain numbers, ``snapshot()`` keeps every legacy key.

Everything is host-side bookkeeping around the engine loop — no device
work, no effect on the two compiled programs.  ``snapshot()`` returns a
plain-dict view the tests and ``bench.py --decode-serving`` read; the
``clock`` is injectable so tests can drive deterministic time.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

from torchgpipe_tpu.obs.registry import (
    MetricsRegistry,
    counter_property as _counter_property,
)


@dataclasses.dataclass
class RequestTimes:
    """Wall-clock milestones of one request (``None`` = not reached)."""

    rid: str
    arrival: float
    admitted: Optional[float] = None
    first_token: Optional[float] = None
    finished: Optional[float] = None
    tokens: int = 0
    status: str = "queued"   # queued|active|finished|cancelled|preempted

    @property
    def queue_wait(self) -> Optional[float]:
        return None if self.admitted is None else self.admitted - self.arrival

    @property
    def ttft(self) -> Optional[float]:
        """Time to first token, from arrival (includes queue wait)."""
        if self.first_token is None:
            return None
        return self.first_token - self.arrival

    @property
    def tpot(self) -> Optional[float]:
        """Time per output token over the decode phase (tokens after the
        first); ``None`` until finished or for single-token outputs."""
        if self.finished is None or self.first_token is None:
            return None
        if self.tokens <= 1:
            return None
        return (self.finished - self.first_token) / (self.tokens - 1)


class ServingMetrics:
    """Counters the serving engine maintains; see the module docstring.

    Series names are fixed (``serving_*``): ONE engine per shared
    registry — a second engine on the same registry merges into the
    same series (its snapshot then reports combined totals).  Give each
    engine its own registry, or its own ``ServingMetrics``, when you
    need them separable.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 registry: Optional[MetricsRegistry] = None) -> None:
        self._clock = clock
        self.registry = registry or MetricsRegistry(clock=clock)
        self.requests: Dict[str, RequestTimes] = {}
        reg = self.registry
        self._c_prefill = reg.counter(
            "serving_prefill_steps", help="compiled prefill steps run")
        self._c_decode = reg.counter(
            "serving_decode_steps", help="compiled decode steps run")
        self._c_occupied = reg.counter(
            "serving_occupied_slot_steps",
            help="slot-steps doing useful work")
        self._c_total = reg.counter(
            "serving_total_slot_steps",
            help="slot-steps available (steps * slots)")
        self._c_tokens = reg.counter(
            "serving_tokens_out", help="tokens emitted")
        self._c_retries = reg.counter(
            "serving_retries", help="transient step retries")
        self._c_drains = reg.counter(
            "serving_drains", help="cooperative drains")
        self._c_preempted = reg.counter(
            "serving_preempted_requests",
            help="unfinished requests at drain time")
        self._c_prefix_hits = reg.counter(
            "serving_prefix_hits",
            help="admissions that reused a cached KV prefix")
        self._c_prefix_tokens = reg.counter(
            "serving_prefix_reused_tokens",
            help="prompt tokens absorbed by KV-prefix copies "
                 "(prefill FLOPs avoided)")
        self._c_migr_out = reg.counter(
            "serving_migrations_out",
            help="requests handed off to a decode replica at prompt "
                 "completion (phase-disaggregated fleets)")
        self._c_migr_in = reg.counter(
            "serving_migrations_in",
            help="requests ingested mid-stream from a prefill replica")
        self._h_ttft = reg.histogram(
            "serving_ttft_seconds", help="time to first token (arrival→)")
        self._h_tpot = reg.histogram(
            "serving_tpot_seconds", help="time per output token (decode)")
        self._h_queue = reg.histogram(
            "serving_queue_wait_seconds", help="arrival→admission wait")
        self.started = clock()

    # Legacy attribute API (all read/assignable ints), registry-backed
    # through the shared facade (obs.registry.counter_property).
    prefill_steps = _counter_property("_c_prefill")
    decode_steps = _counter_property("_c_decode")
    occupied_slot_steps = _counter_property("_c_occupied")
    total_slot_steps = _counter_property("_c_total")
    tokens_out = _counter_property("_c_tokens")
    retries = _counter_property("_c_retries")
    drains = _counter_property("_c_drains")
    preempted_requests = _counter_property("_c_preempted")
    prefix_hits = _counter_property("_c_prefix_hits")
    prefix_reused_tokens = _counter_property("_c_prefix_tokens")
    migrations_out = _counter_property("_c_migr_out")
    migrations_in = _counter_property("_c_migr_in")

    # ------------------------------------------------------------------ #
    # request lifecycle                                                  #
    # ------------------------------------------------------------------ #

    def now(self) -> float:
        return self._clock()

    def arrived(self, rid: str) -> None:
        self.requests[rid] = RequestTimes(rid=rid, arrival=self._clock())

    def admitted(self, rid: str) -> None:
        r = self.requests[rid]
        r.admitted = self._clock()
        r.status = "active"
        wait = r.queue_wait
        if wait is not None:
            self._h_queue.observe(wait)

    def token(self, rid: str) -> None:
        r = self.requests[rid]
        t = self._clock()
        if r.first_token is None:
            r.first_token = t
            ttft = r.ttft
            if ttft is not None:
                self._h_ttft.observe(ttft)
        r.tokens += 1
        self._c_tokens.inc()

    def ingested(self, rid: str) -> None:
        """A migrated request arriving mid-stream (disaggregated
        serving): its FIRST token was emitted on the donor prefill
        replica, so this engine's first emission must count toward
        TPOT, never as a second TTFT — ``first_token`` is stamped now
        and ``tokens`` starts at the one token already streamed."""
        r = self.requests[rid]
        t = self._clock()
        if r.admitted is None:
            r.admitted = t
        r.status = "active"
        r.first_token = t
        r.tokens = 1
        self._c_migr_in.inc()

    def migrated_out(self, rid: str) -> None:
        """The donor side of :meth:`ingested`: the request left this
        replica at prompt completion.  No latency histogram fires —
        the stream continues elsewhere; only the handoff is counted."""
        r = self.requests[rid]
        r.status = "migrated"
        self._c_migr_out.inc()

    def finished(self, rid: str, status: str = "finished") -> None:
        r = self.requests[rid]
        r.finished = self._clock()
        r.status = status
        tpot = r.tpot
        if tpot is not None and status == "finished":
            self._h_tpot.observe(tpot)

    # ------------------------------------------------------------------ #
    # engine iterations                                                  #
    # ------------------------------------------------------------------ #

    def step(self, kind: str, active_slots: int, num_slots: int) -> None:
        if kind == "prefill":
            self._c_prefill.inc()
        else:
            self._c_decode.inc()
        self._c_occupied.inc(active_slots)
        self._c_total.inc(num_slots)

    def drained(self, unfinished: int) -> None:
        self._c_drains.inc()
        self._c_preempted.inc(unfinished)

    def prefix_hit(self, reused_tokens: int) -> None:
        """One admission reused ``reused_tokens`` prompt tokens from the
        KV prefix cache (prefill work avoided)."""
        self._c_prefix_hits.inc()
        self._c_prefix_tokens.inc(reused_tokens)

    # ------------------------------------------------------------------ #
    # snapshot                                                           #
    # ------------------------------------------------------------------ #

    @property
    def engine_steps(self) -> int:
        return self.prefill_steps + self.decode_steps

    @property
    def occupancy(self) -> float:
        """Mean fraction of slot-steps doing useful work."""
        if self.total_slot_steps == 0:
            return 0.0
        return self.occupied_slot_steps / self.total_slot_steps

    def snapshot(self) -> Dict[str, Any]:
        """A plain-dict view: engine aggregates, latency percentiles
        (p50/p95/p99 TTFT and TPOT from the registry histograms — None
        until a request reaches the milestone) + per-request rows."""
        now = self._clock()
        elapsed = max(now - self.started, 1e-9)
        per_request: List[Dict[str, Any]] = []
        for r in self.requests.values():
            per_request.append({
                "rid": r.rid,
                "status": r.status,
                "tokens": r.tokens,
                "queue_wait": r.queue_wait,
                "ttft": r.ttft,
                "tpot": r.tpot,
            })
        return {
            "engine_steps": self.engine_steps,
            "prefill_steps": self.prefill_steps,
            "decode_steps": self.decode_steps,
            "tokens_out": self.tokens_out,
            "tokens_per_sec": self.tokens_out / elapsed,
            "tokens_per_step": (
                self.tokens_out / self.engine_steps
                if self.engine_steps else 0.0
            ),
            "occupancy": self.occupancy,
            "retries": self.retries,
            "drains": self.drains,
            "preempted_requests": self.preempted_requests,
            "prefix_hits": self.prefix_hits,
            "prefix_reused_tokens": self.prefix_reused_tokens,
            "migrations_out": self.migrations_out,
            "migrations_in": self.migrations_in,
            "ttft_p50": self._h_ttft.percentile(0.50),
            "ttft_p95": self._h_ttft.percentile(0.95),
            "ttft_p99": self._h_ttft.percentile(0.99),
            "tpot_p50": self._h_tpot.percentile(0.50),
            "tpot_p95": self._h_tpot.percentile(0.95),
            "tpot_p99": self._h_tpot.percentile(0.99),
            "queue_wait_p50": self._h_queue.percentile(0.50),
            "queue_wait_p95": self._h_queue.percentile(0.95),
            "requests": per_request,
        }


__all__ = ["RequestTimes", "ServingMetrics"]
