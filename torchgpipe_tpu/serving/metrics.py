"""Serving observability: per-request latency and engine-level counters.

The two layers a decode server is judged by (Orca, OSDI '22; vLLM,
arXiv:2309.06180):

* **Per-request latency** — :class:`RequestTimes` tracks arrival →
  admission → first token → finish, from which the standard quantities
  derive: queue wait (admitted − arrival), TTFT (first token − arrival),
  TPOT (decode time per subsequent token).
* **Engine throughput** — per-iteration counters: how many compiled
  steps of each kind ran, how many slot-steps were occupied vs idle
  (occupancy is THE continuous-batching win: recycled slots keep the
  batch dim full), tokens emitted, transient retries, drains and the
  requests they preempted.

Everything is host-side bookkeeping around the engine loop — no device
work, no effect on the two compiled programs.  ``snapshot()`` returns a
plain-dict view the tests and ``bench.py --decode-serving`` read; the
``clock`` is injectable so tests can drive deterministic time.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional


@dataclasses.dataclass
class RequestTimes:
    """Wall-clock milestones of one request (``None`` = not reached)."""

    rid: str
    arrival: float
    admitted: Optional[float] = None
    first_token: Optional[float] = None
    finished: Optional[float] = None
    tokens: int = 0
    status: str = "queued"   # queued|active|finished|cancelled|preempted

    @property
    def queue_wait(self) -> Optional[float]:
        return None if self.admitted is None else self.admitted - self.arrival

    @property
    def ttft(self) -> Optional[float]:
        """Time to first token, from arrival (includes queue wait)."""
        if self.first_token is None:
            return None
        return self.first_token - self.arrival

    @property
    def tpot(self) -> Optional[float]:
        """Time per output token over the decode phase (tokens after the
        first); ``None`` until finished or for single-token outputs."""
        if self.finished is None or self.first_token is None:
            return None
        if self.tokens <= 1:
            return None
        return (self.finished - self.first_token) / (self.tokens - 1)


class ServingMetrics:
    """Counters the serving engine maintains; see the module docstring."""

    def __init__(self, clock: Callable[[], float] = time.monotonic) -> None:
        self._clock = clock
        self.requests: Dict[str, RequestTimes] = {}
        self.prefill_steps = 0
        self.decode_steps = 0
        self.occupied_slot_steps = 0   # slot-steps doing useful work
        self.total_slot_steps = 0      # slot-steps available (steps * slots)
        self.tokens_out = 0
        self.retries = 0
        self.drains = 0
        self.preempted_requests = 0    # unfinished requests at drain time
        self.started = clock()

    # ------------------------------------------------------------------ #
    # request lifecycle                                                  #
    # ------------------------------------------------------------------ #

    def now(self) -> float:
        return self._clock()

    def arrived(self, rid: str) -> None:
        self.requests[rid] = RequestTimes(rid=rid, arrival=self._clock())

    def admitted(self, rid: str) -> None:
        r = self.requests[rid]
        r.admitted = self._clock()
        r.status = "active"

    def token(self, rid: str) -> None:
        r = self.requests[rid]
        t = self._clock()
        if r.first_token is None:
            r.first_token = t
        r.tokens += 1
        self.tokens_out += 1

    def finished(self, rid: str, status: str = "finished") -> None:
        r = self.requests[rid]
        r.finished = self._clock()
        r.status = status

    # ------------------------------------------------------------------ #
    # engine iterations                                                  #
    # ------------------------------------------------------------------ #

    def step(self, kind: str, active_slots: int, num_slots: int) -> None:
        if kind == "prefill":
            self.prefill_steps += 1
        else:
            self.decode_steps += 1
        self.occupied_slot_steps += active_slots
        self.total_slot_steps += num_slots

    def drained(self, unfinished: int) -> None:
        self.drains += 1
        self.preempted_requests += unfinished

    # ------------------------------------------------------------------ #
    # snapshot                                                           #
    # ------------------------------------------------------------------ #

    @property
    def engine_steps(self) -> int:
        return self.prefill_steps + self.decode_steps

    @property
    def occupancy(self) -> float:
        """Mean fraction of slot-steps doing useful work."""
        if self.total_slot_steps == 0:
            return 0.0
        return self.occupied_slot_steps / self.total_slot_steps

    def snapshot(self) -> Dict[str, Any]:
        """A plain-dict view: engine aggregates + per-request rows."""
        now = self._clock()
        elapsed = max(now - self.started, 1e-9)
        per_request: List[Dict[str, Any]] = []
        for r in self.requests.values():
            per_request.append({
                "rid": r.rid,
                "status": r.status,
                "tokens": r.tokens,
                "queue_wait": r.queue_wait,
                "ttft": r.ttft,
                "tpot": r.tpot,
            })
        return {
            "engine_steps": self.engine_steps,
            "prefill_steps": self.prefill_steps,
            "decode_steps": self.decode_steps,
            "tokens_out": self.tokens_out,
            "tokens_per_sec": self.tokens_out / elapsed,
            "tokens_per_step": (
                self.tokens_out / self.engine_steps
                if self.engine_steps else 0.0
            ),
            "occupancy": self.occupancy,
            "retries": self.retries,
            "drains": self.drains,
            "preempted_requests": self.preempted_requests,
            "requests": per_request,
        }


__all__ = ["RequestTimes", "ServingMetrics"]
