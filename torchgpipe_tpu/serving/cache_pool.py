"""Slot-pooled KV caches: fixed banks, free-list allocation, recycling.

The TPU serving problem in one sentence: request churn must never change
an array shape (XLA recompiles per shape — the ``recompilation-hazard``
lint rule), yet requests arrive, finish and cancel at arbitrary times.
The pool squares that circle the PagedAttention/Orca way, specialised to
one page per request: a fixed ``[num_slots, max_len, kv_heads, head_dim]``
K/V bank per layer (a :class:`~torchgpipe_tpu.models.generation.KVCache`
or int8 :class:`~torchgpipe_tpu.models.generation.QuantKVCache` whose
batch dim IS the slot dim), a host-side free list handing slots to
requests and taking them back, and a per-slot ``lengths`` vector (host
mirror, passed into every compiled step) giving each slot its own
sequence frontier.

Recycling needs NO device work: a freed slot's stale rows are dead by
masking — every attention read masks cache rows ``> length``, and decode
writes land exactly at ``length``, so a recycled slot can never see its
previous tenant's K/V, scales included (the bitwise slot-reuse test in
``tests/test_serving.py`` pins this for the int8 cache, where a stale
*scale* would corrupt every row it spans).

Sizing: :func:`torchgpipe_tpu.tune.serving_cache_bytes` accounts the
pool via ``eval_shape`` (no allocation);
:func:`torchgpipe_tpu.tune.serving_max_slots` inverts it against an HBM
budget — the scheduler's admission control reads that number.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from torchgpipe_tpu.models.generation import init_cache, init_quant_cache
from torchgpipe_tpu.models.transformer import TransformerConfig


class CachePool:
    """A fixed-shape KV bank + free-list slot allocator.

    The device state (``cache``) is intentionally PUBLIC and replaced
    wholesale by the engine after every compiled step — the pool object
    owns allocation bookkeeping (host-side, O(1) per event), not the
    arrays' life cycle.  ``lengths`` is the host mirror of per-slot
    frontiers: the engine advances it deterministically (it knows
    exactly how many tokens each step absorbed), so steady-state serving
    never fetches it back from the device.
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        num_slots: int,
        max_len: int,
        *,
        kv_quant: bool = False,
        dtype: Optional[Any] = None,
    ) -> None:
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if max_len < 2:
            raise ValueError(
                f"max_len must hold a prompt plus one generated token, "
                f"got {max_len}"
            )
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_len = max_len
        self.kv_quant = kv_quant
        self.dtype = dtype
        self.cache: Any = (
            init_quant_cache(cfg, num_slots, max_len)
            if kv_quant
            else init_cache(cfg, num_slots, max_len, dtype=dtype)
        )
        self.lengths = np.zeros((num_slots,), np.int32)
        # LIFO free list: the most-recently-freed slot is reused first,
        # maximising the chance its rows are still warm in cache AND
        # exercising the stale-row masking continuously.
        self._free: List[int] = list(range(num_slots - 1, -1, -1))
        self._owner: Dict[int, str] = {}
        # Per-slot refcounts: alloc() hands the owner one reference;
        # retain() adds more (the fleet prefix cache pins donor slots
        # this way).  A slot re-enters the LIFO free list only when the
        # LAST reference releases — a pinned slot outlives its request
        # and can never be recycled while something still reads its
        # rows (the refcount invariant tools/fleet_verify.py churns).
        self._refs: Dict[int, int] = {}

    # ------------------------------------------------------------------ #
    # allocation                                                         #
    # ------------------------------------------------------------------ #

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_active(self) -> int:
        """Slots owned by a live request (pinned-only slots excluded)."""
        return len(self._owner)

    @property
    def num_pinned(self) -> int:
        """Slots kept out of the free list ONLY by extra references
        (``retain``) — typically prefix-cache donors whose request has
        finished."""
        return self.num_slots - len(self._free) - len(self._owner)

    def alloc(self, owner: str) -> Optional[int]:
        """Hand a free slot to ``owner`` (its frontier reset to 0, one
        reference), or ``None`` when the pool is exhausted."""
        if not self._free:
            return None
        slot = self._free.pop()
        self._owner[slot] = owner
        self._refs[slot] = 1
        self.lengths[slot] = 0
        return slot

    def retain(self, slot: int) -> int:
        """Add a reference to an allocated/pinned slot (the prefix
        cache's donor pin); returns the new refcount."""
        if slot not in self._refs:
            raise KeyError(f"slot {slot} is not allocated")
        self._refs[slot] += 1
        return self._refs[slot]

    def refcount(self, slot: int) -> int:
        return self._refs.get(slot, 0)

    def free(self, slot: int) -> None:
        """The OWNER's release: the slot loses its request but recycles
        only when no extra references pin it (refcount 0).  No device
        work either way: stale rows are dead by masking (see the module
        docstring)."""
        if slot not in self._owner:
            raise KeyError(f"slot {slot} is not allocated")
        del self._owner[slot]
        self.release(slot)

    def release(self, slot: int) -> None:
        """Drop one (non-owner) reference; at refcount 0 the slot
        re-enters the LIFO free list with its frontier zeroed."""
        refs = self._refs.get(slot)
        if refs is None:
            raise KeyError(f"slot {slot} is not allocated")
        if refs > 1:
            self._refs[slot] = refs - 1
            return
        del self._refs[slot]
        self.lengths[slot] = 0
        self._free.append(slot)

    def owner_of(self, slot: int) -> Optional[str]:
        return self._owner.get(slot)

    def active_slots(self) -> List[int]:
        return sorted(self._owner)

    def check_refcounts(self) -> None:
        """Structural invariants, for tests and the fleet-verify churn
        grid: free/referenced partition the slots, every owned slot is
        referenced, refcounts are positive.  Raises (never ``assert``
        — the gate must stay live under ``python -O``)."""
        free = set(self._free)
        reffed = set(self._refs)
        if free & reffed:
            raise RuntimeError(
                f"slots both free and referenced: {sorted(free & reffed)}"
            )
        if free | reffed != set(range(self.num_slots)):
            raise RuntimeError(
                f"free {sorted(free)} + referenced {sorted(reffed)} do "
                f"not partition the {self.num_slots} slots"
            )
        if not set(self._owner) <= reffed:
            raise RuntimeError(
                f"owned slots {sorted(set(self._owner) - reffed)} carry "
                "no reference"
            )
        if any(n < 1 for n in self._refs.values()):
            raise RuntimeError(f"non-positive refcount: {self._refs}")

    # ------------------------------------------------------------------ #
    # accounting                                                         #
    # ------------------------------------------------------------------ #

    def bytes(self) -> int:
        """Bytes this pool's device arrays pin (eval_shape accounting —
        equals the allocated size)."""
        from torchgpipe_tpu.tune import serving_cache_bytes

        return serving_cache_bytes(
            self.cfg, self.num_slots, self.max_len,
            kv_quant=self.kv_quant, dtype=self.dtype,
        )

    def lengths_device(self) -> jnp.ndarray:
        """The per-slot frontier vector as an int32 array for a step.

        SNAPSHOT semantics, deliberately: ``jnp.asarray`` on CPU may
        alias the numpy buffer zero-copy, and the engine mutates
        ``self.lengths`` in place right after dispatching the
        (asynchronously executing) step that reads it — without the copy
        the program races the host update (observed as nondeterministic
        outputs on the CPU backend)."""
        return jnp.asarray(self.lengths.copy())


__all__ = ["CachePool"]
