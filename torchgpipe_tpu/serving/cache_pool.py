"""Slot-pooled KV caches: fixed banks, free-list allocation, recycling.

The TPU serving problem in one sentence: request churn must never change
an array shape (XLA recompiles per shape — the ``recompilation-hazard``
lint rule), yet requests arrive, finish and cancel at arbitrary times.
The pool squares that circle the PagedAttention/Orca way, specialised to
one page per request: a fixed ``[num_slots, max_len, kv_heads, head_dim]``
K/V bank per layer (a :class:`~torchgpipe_tpu.models.generation.KVCache`
or int8 :class:`~torchgpipe_tpu.models.generation.QuantKVCache` whose
batch dim IS the slot dim), a host-side free list handing slots to
requests and taking them back, and a per-slot ``lengths`` vector (host
mirror, passed into every compiled step) giving each slot its own
sequence frontier.

Recycling needs NO device work: a freed slot's stale rows are dead by
masking — every attention read masks cache rows ``> length``, and decode
writes land exactly at ``length``, so a recycled slot can never see its
previous tenant's K/V, scales included (the bitwise slot-reuse test in
``tests/test_serving.py`` pins this for the int8 cache, where a stale
*scale* would corrupt every row it spans).

Sizing: :func:`torchgpipe_tpu.tune.serving_cache_bytes` accounts the
pool via ``eval_shape`` (no allocation);
:func:`torchgpipe_tpu.tune.serving_max_slots` inverts it against an HBM
budget — the scheduler's admission control reads that number.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from torchgpipe_tpu.models.generation import init_cache, init_quant_cache
from torchgpipe_tpu.models.transformer import TransformerConfig


class CachePool:
    """A fixed-shape KV bank + free-list slot allocator.

    The device state (``cache``) is intentionally PUBLIC and replaced
    wholesale by the engine after every compiled step — the pool object
    owns allocation bookkeeping (host-side, O(1) per event), not the
    arrays' life cycle.  ``lengths`` is the host mirror of per-slot
    frontiers: the engine advances it deterministically (it knows
    exactly how many tokens each step absorbed), so steady-state serving
    never fetches it back from the device.
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        num_slots: int,
        max_len: int,
        *,
        kv_quant: bool = False,
        dtype: Optional[Any] = None,
    ) -> None:
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if max_len < 2:
            raise ValueError(
                f"max_len must hold a prompt plus one generated token, "
                f"got {max_len}"
            )
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_len = max_len
        self.kv_quant = kv_quant
        self.dtype = dtype
        self.cache: Any = (
            init_quant_cache(cfg, num_slots, max_len)
            if kv_quant
            else init_cache(cfg, num_slots, max_len, dtype=dtype)
        )
        self.lengths = np.zeros((num_slots,), np.int32)
        # LIFO free list: the most-recently-freed slot is reused first,
        # maximising the chance its rows are still warm in cache AND
        # exercising the stale-row masking continuously.
        self._free: List[int] = list(range(num_slots - 1, -1, -1))
        self._owner: Dict[int, str] = {}

    # ------------------------------------------------------------------ #
    # allocation                                                         #
    # ------------------------------------------------------------------ #

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_active(self) -> int:
        return self.num_slots - len(self._free)

    def alloc(self, owner: str) -> Optional[int]:
        """Hand a free slot to ``owner`` (its frontier reset to 0), or
        ``None`` when the pool is exhausted."""
        if not self._free:
            return None
        slot = self._free.pop()
        self._owner[slot] = owner
        self.lengths[slot] = 0
        return slot

    def free(self, slot: int) -> None:
        """Recycle a slot.  No device work: stale rows are dead by
        masking (see the module docstring)."""
        if slot not in self._owner:
            raise KeyError(f"slot {slot} is not allocated")
        del self._owner[slot]
        self.lengths[slot] = 0
        self._free.append(slot)

    def owner_of(self, slot: int) -> Optional[str]:
        return self._owner.get(slot)

    def active_slots(self) -> List[int]:
        return sorted(self._owner)

    # ------------------------------------------------------------------ #
    # accounting                                                         #
    # ------------------------------------------------------------------ #

    def bytes(self) -> int:
        """Bytes this pool's device arrays pin (eval_shape accounting —
        equals the allocated size)."""
        from torchgpipe_tpu.tune import serving_cache_bytes

        return serving_cache_bytes(
            self.cfg, self.num_slots, self.max_len,
            kv_quant=self.kv_quant, dtype=self.dtype,
        )

    def lengths_device(self) -> jnp.ndarray:
        """The per-slot frontier vector as an int32 array for a step.

        SNAPSHOT semantics, deliberately: ``jnp.asarray`` on CPU may
        alias the numpy buffer zero-copy, and the engine mutates
        ``self.lengths`` in place right after dispatching the
        (asynchronously executing) step that reads it — without the copy
        the program races the host update (observed as nondeterministic
        outputs on the CPU backend)."""
        return jnp.asarray(self.lengths.copy())


__all__ = ["CachePool"]
