"""Iteration-level (continuous) batching: Orca-style request scheduling.

The scheduler decides, BETWEEN compiled steps, three things the compiled
programs never see (they only ever see values, not shapes):

* **Admission** — queued requests move into free pool slots the moment
  one opens (a finished/cancelled request frees its slot in the SAME
  engine iteration), subject to the admission-control cap: with an HBM
  budget the cap is :func:`torchgpipe_tpu.tune.serving_max_slots`'s
  ``eval_shape`` accounting of the cache pool — admitting a request can
  never grow an array, so the cap is the entire memory story.
  ``wave_admission=True`` disables recycling (admit only into an EMPTY
  engine, run the wave to its longest request) — the static-batching
  baseline the benchmarks compare against.
* **Phase interleaving** — a request absorbs its prompt in fixed-size
  chunks (``prefill_chunk``) through the same slot-masked step decode
  uses; when both prefill work and decode-ready rows exist, the
  scheduler ALTERNATES so ongoing decodes are never starved behind a
  long prompt (chunked prefill, Orca §4/Sarathi-style).  A bucket
  LADDER (``prefill_chunk=(1, 2, 4, 8)``) admits each step at the
  smallest bucket covering its pending work, so short prompts stop
  paying the max chunk's FLOPs while the compiled-program count stays
  statically bounded at ``len(ladder) + 1`` (docs/serving.md).
* **Eviction** — finished (per-row EOS / max-token) and cancelled
  requests release their slot immediately.

Everything here is host-side and O(active + queued) per iteration.
"""

from __future__ import annotations

import dataclasses
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
    Union,
)

import numpy as np

from torchgpipe_tpu.serving.cache_pool import CachePool


def normalize_buckets(
    prefill_chunk: Union[int, Sequence[int]]
) -> Tuple[int, ...]:
    """The prefill BUCKET LADDER from a ``prefill_chunk`` declaration:
    a single int is the classic one-chunk configuration; a sequence is a
    static ladder of chunk sizes (sorted, deduplicated), each compiling
    ONE program — a prefill step picks the smallest bucket covering its
    work, so short prompts stop paying the max chunk's FLOPs while the
    steady-state program count stays statically bounded at
    ``len(ladder) + 1`` (``analysis.serving`` certifies this)."""
    if isinstance(prefill_chunk, (int, np.integer)):
        buckets: Tuple[int, ...] = (int(prefill_chunk),)
    else:
        buckets = tuple(sorted({int(g) for g in prefill_chunk}))
    if not buckets or buckets[0] < 1:
        raise ValueError(
            f"prefill buckets must be >= 1, got {prefill_chunk!r}"
        )
    return buckets


@dataclasses.dataclass
class Request:
    """One generation request plus its runtime state.

    ``prompt`` is the tokens to teacher-force (for a resumed request:
    original prompt + tokens already emitted before the drain, with
    ``emitted_prefix`` carrying the latter so results concatenate).
    """

    rid: str
    prompt: np.ndarray                    # [s] int32
    max_new_tokens: int
    eos_id: Optional[int] = None
    on_token: Optional[Callable[[str, int], None]] = None
    emitted_prefix: List[int] = dataclasses.field(default_factory=list)
    # QoS identity (serving/qos.py): the latency tier admission orders
    # by, and the tenant token budgets are charged to.  Both ride the
    # drain/restore snapshot, so a preempted or migrated request keeps
    # its class wherever it resumes.
    tier: str = "standard"
    tenant: Optional[str] = None

    # runtime state (engine/scheduler owned)
    status: str = "queued"   # queued|active|finished|cancelled|preempted
    slot: Optional[int] = None
    prefilled: int = 0       # prompt tokens absorbed so far
    generated: List[int] = dataclasses.field(default_factory=list)

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def prefill_done(self) -> bool:
        return self.prefilled >= self.prompt_len

    @property
    def remaining_new(self) -> int:
        return self.max_new_tokens - len(self.generated)

    def tokens(self) -> List[int]:
        """All tokens this request has produced (across a drain/resume)."""
        return list(self.emitted_prefix) + list(self.generated)


class Scheduler:
    """Continuous-batching admission/interleave/eviction policy."""

    def __init__(
        self,
        pool: CachePool,
        *,
        prefill_chunk: Union[int, Sequence[int]] = 8,
        max_active: Optional[int] = None,
        wave_admission: bool = False,
        qos: Optional[Any] = None,
    ) -> None:
        self.prefill_buckets = normalize_buckets(prefill_chunk)
        self.pool = pool
        # The classic single-chunk attribute stays the LADDER MAX — the
        # largest program any prefill step can dispatch.
        self.prefill_chunk = self.prefill_buckets[-1]
        self.max_active = (
            pool.num_slots if max_active is None
            else min(max_active, pool.num_slots)
        )
        if self.max_active < 1:
            raise ValueError(
                "admission cap is 0 slots: the cache pool does not fit "
                "the HBM budget — shrink max_len/num_slots or raise the "
                "budget (tune.serving_max_slots accounting)"
            )
        self.wave_admission = wave_admission
        # ``qos`` (serving.qos.QosPolicy) turns FIFO admission into
        # tier-ordered admission and resolves over-budget demotion at
        # pick time; None keeps classic FIFO exactly (and requests with
        # uniform tiers admit FIFO either way — the stable tie-break).
        self.qos = qos
        self.queue: List[Request] = []
        self.active: Dict[str, Request] = {}
        self._last_action = "decode"  # alternation seed: prefill first

    # ------------------------------------------------------------------ #
    # request lifecycle                                                  #
    # ------------------------------------------------------------------ #

    def submit(self, req: Request) -> None:
        if req.prompt_len < 1:
            raise ValueError(f"request {req.rid!r}: empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.rid!r}: max_new_tokens must be >= 1"
            )
        if req.prompt_len + req.max_new_tokens > self.pool.max_len:
            raise ValueError(
                f"request {req.rid!r}: prompt ({req.prompt_len}) + "
                f"max_new_tokens ({req.max_new_tokens}) exceeds the "
                f"pool's max_len={self.pool.max_len} — shape-static "
                "serving cannot grow a slot; raise max_len at engine "
                "build time or shorten the request"
            )
        self.queue.append(req)

    def cancel(self, rid: str) -> bool:
        """Cancel a queued or active request; its slot frees NOW."""
        for i, req in enumerate(self.queue):
            if req.rid == rid:
                req.status = "cancelled"
                del self.queue[i]
                return True
        req = self.active.get(rid)
        if req is not None:
            req.status = "cancelled"
            self.release(req)
            return True
        return False

    def admit(self) -> List[Request]:
        """Move queued requests into free slots (iteration-level).

        Continuous mode admits whenever a slot is free under the cap;
        wave mode only into an idle engine (static-batching baseline)."""
        admitted: List[Request] = []
        if self.wave_admission and self.active:
            return admitted
        while (
            self.queue
            and self.pool.num_free > 0
            and len(self.active) < self.max_active
        ):
            req = self.queue.pop(self._pick_next())
            slot = self.pool.alloc(req.rid)
            assert slot is not None
            req.slot = slot
            req.status = "active"
            self.active[req.rid] = req
            admitted.append(req)
        return admitted

    def _pick_next(self) -> int:
        """The queue index the next free slot admits: highest tier
        priority first (interactive < standard < batch), arrival order
        within a tier — without a QoS policy, plain FIFO.  Over-budget
        demotion resolves HERE, against the tenant's LATEST spend: the
        demotion sticks on the request (``req.tier``), so its drain and
        migration snapshots carry the class it actually ran at, and it
        is counted once per request."""
        if self.qos is None:
            return 0
        from torchgpipe_tpu.serving.qos import TIER_PRIORITY

        best, best_rank = 0, None
        for i, req in enumerate(self.queue):
            eff = self.qos.effective_tier(req.tier, req.tenant)
            if eff != req.tier:
                req.tier = eff
                self.qos.note_demotion(req.tenant)
            rank = TIER_PRIORITY[req.tier]
            if best_rank is None or rank < best_rank:
                best, best_rank = i, rank
        return best

    def release(self, req: Request) -> None:
        """Free a finished/cancelled/preempted request's slot NOW — the
        per-row early-exit that makes batching continuous."""
        if req.slot is not None:
            self.pool.free(req.slot)
            req.slot = None
        self.active.pop(req.rid, None)

    # ------------------------------------------------------------------ #
    # iteration policy                                                   #
    # ------------------------------------------------------------------ #

    def prefill_pending(self) -> List[Request]:
        return [r for r in self.active.values() if not r.prefill_done]

    def bucket_for(self, n: int) -> int:
        """The smallest ladder bucket covering ``n`` pending prompt
        tokens (the max bucket when ``n`` exceeds it — the remainder
        absorbs over further chunked steps)."""
        for g in self.prefill_buckets:
            if n <= g:
                return g
        return self.prefill_buckets[-1]

    def prefill_bucket(self) -> int:
        """The bucket THIS prefill step dispatches: the smallest ladder
        entry covering every pending request's next chunk (each request's
        chunk is its remaining prompt capped at the ladder max — one
        shared ``[slots, g]`` buffer serves all slots, masked rows
        no-ops, so the step's bucket must cover the largest take)."""
        need = 0
        cap = self.prefill_buckets[-1]
        for r in self.prefill_pending():
            need = max(need, min(r.prompt_len - r.prefilled, cap))
        return self.bucket_for(max(need, 1))

    def decode_ready(self) -> List[Request]:
        return [r for r in self.active.values() if r.prefill_done]

    def next_action(self) -> Optional[str]:
        """``'prefill'`` | ``'decode'`` | ``None`` (idle).

        When both phases have work the scheduler alternates (chunked
        prefill interleaving); otherwise whichever phase has work runs.
        """
        pre = bool(self.prefill_pending())
        dec = bool(self.decode_ready())
        if pre and dec:
            action = "decode" if self._last_action == "prefill" else "prefill"
        elif pre:
            action = "prefill"
        elif dec:
            action = "decode"
        else:
            return None
        self._last_action = action
        return action

    @property
    def queue_depth(self) -> int:
        """Requests waiting for a slot — recorded on each request's
        ``req_submit`` flight event so a stitched trace can say how
        deep the line was when this request joined it."""
        return len(self.queue)

    @property
    def idle(self) -> bool:
        return not self.queue and not self.active


__all__ = ["Request", "Scheduler", "normalize_buckets"]
