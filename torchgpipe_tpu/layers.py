"""Sequential layer abstraction — the model container the pipeline partitions.

The reference wraps ``nn.Sequential`` (reference: torchgpipe/gpipe.py:211-255)
and relies on PyTorch modules carrying their own parameters.  The TPU-native
equivalent is functional: a model is a list of :class:`Layer` values, each an
``(init, apply)`` pair over explicit parameter/state pytrees:

    params, state = layer.init(rng, in_spec)
    y, new_state  = layer.apply(params, state, x, rng=rng, train=True)

* ``params`` — trainable pytree (differentiated).
* ``state``  — non-trainable pytree (e.g. BatchNorm running stats), threaded
  through the micro-batch loop (replaces in-place buffer mutation).
* ``rng``    — a ``jax.random`` key; counter-based folding replaces the
  reference's RNG state capture/restore for recompute determinism
  (reference: torchgpipe/checkpoint.py:191-231).
* ``train``  — static flag; separate traces for train/eval replace runtime
  branching.

Layer ``apply`` functions must be pure and traceable (jit/vjp/vmap-safe).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Pytree = Any
Spec = Any  # pytree of jax.ShapeDtypeStruct
InitFn = Callable[..., Tuple[Pytree, Pytree]]
ApplyFn = Callable[..., Tuple[Any, Pytree]]


@dataclasses.dataclass(frozen=True)
class Layer:
    """One element of a sequential model.

    ``stash``/``pop`` declare named skip connections (see
    :mod:`torchgpipe_tpu.skip`); plain layers leave them empty.
    """

    name: str
    init: InitFn  # (rng, in_spec) -> (params, state)
    apply: ApplyFn  # (params, state, x, *, rng, train) -> (y, new_state)
    stash: Tuple[Any, ...] = ()  # names this layer stashes ((ns, name) tuples)
    pop: Tuple[Any, ...] = ()  # names this layer pops
    meta: Any = None  # structured description (e.g. batch-norm hyperparams)
                      # enabling layer conversions like deferred batch-norm

    def out_spec(self, in_spec: Spec, *, train: bool = True) -> Spec:
        """Shape-infer the layer output without running it."""
        params, state = jax.eval_shape(
            lambda r: self.init(r, in_spec), jax.random.PRNGKey(0)
        )

        def run(p, s, x):
            y, _ = self.apply(p, s, x, rng=jax.random.PRNGKey(0), train=train)
            return y

        x = jax.tree_util.tree_map(
            lambda sd: jnp.zeros(sd.shape, sd.dtype), in_spec
        )
        return jax.eval_shape(run, params, state, x)


def stateless(name: str, fn: Callable[[Any], Any]) -> Layer:
    """A parameter-free, state-free layer (activation, reshape, pool...)."""

    def init(rng, in_spec):
        del rng, in_spec
        return (), ()

    def apply(params, state, x, *, rng=None, train=True):
        del params, rng, train
        return fn(x), state

    return Layer(name=name, init=init, apply=apply)


def identity(name: str = "identity") -> Layer:
    """Pass-through layer (reference pattern: nn.Identity in
    benchmarks/models/amoebanet/operations.py:43-49)."""
    return stateless(name, lambda x: x)


def structured(
    name: str,
    children: "dict[str, Layer]",
    fwd: Callable,
    *,
    rebuild: Optional[Callable] = None,
) -> Layer:
    """Compound layer: an arbitrary DAG wiring of named sub-layers.

    ``fwd(run, x) -> y`` expresses the wiring, where ``run(child_name, x)``
    applies the named child exactly once.  This is how non-sequential model
    cells (AmoebaNet NAS cells, FactorizedReduce, residual projections) are
    built without a module system: parameters/state are dicts keyed by child
    name.  The reference reaches for ``nn.Module`` composition here
    (reference: benchmarks/models/amoebanet/__init__.py:65-135).

    ``init`` runs the same wiring with zero inputs, initializing each child
    from the spec of the value actually reaching it — so builders never have
    to hand-propagate intermediate shapes.  The layer carries compound
    ``meta`` so structural transforms (e.g. deferred batch-norm conversion)
    can recurse into the children and rebuild the cell.
    """
    children = dict(children)
    order = {k: i for i, k in enumerate(children)}

    def init(rng, in_spec):
        # Phase 1: abstractly trace the wiring to learn each child's input
        # spec — no device compute, even for full-size models.
        in_specs: dict = {}

        def trace(x, trace_rng):
            def run(cname, xv):
                child = children[cname]
                if cname in in_specs:
                    raise ValueError(
                        f"structured layer {name!r} applies child {cname!r} twice"
                    )
                spec = jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), xv
                )
                in_specs[cname] = spec
                p, s = child.init(
                    jax.random.fold_in(trace_rng, order[cname]), spec
                )
                y, _ = child.apply(p, s, xv, rng=None, train=False)
                return y

            fwd(run, x)
            return ()

        x = jax.tree_util.tree_map(
            lambda sd: jnp.zeros(sd.shape, sd.dtype), in_spec
        )
        jax.eval_shape(trace, x, rng)
        missing = set(children) - set(in_specs)
        if missing:
            raise ValueError(
                f"structured layer {name!r} never applied children {sorted(missing)}"
            )

        # Phase 2: concrete per-child init from the recorded specs.
        params: dict = {}
        state: dict = {}
        for cname, child in children.items():
            p, s = child.init(
                jax.random.fold_in(rng, order[cname]), in_specs[cname]
            )
            params[cname], state[cname] = p, s
        return params, state

    def apply(params, state, x, *, rng=None, train=True):
        st = state if state else {k: () for k in children}
        new_state: dict = {}

        def run(cname, x):
            child = children[cname]
            crng = (
                jax.random.fold_in(rng, order[cname]) if rng is not None else None
            )
            y, ns = child.apply(
                params[cname], st[cname], x, rng=crng, train=train
            )
            new_state[cname] = ns
            return y

        y = fwd(run, x)
        return y, new_state

    if rebuild is None:
        def rebuild(new_children):
            return structured(name, new_children, fwd)

    return Layer(
        name=name,
        init=init,
        apply=apply,
        meta={"kind": "compound", "children": children, "rebuild": rebuild},
    )


def map_layer_tree(layer: Layer, leaf_fn: Callable[[Layer], Layer]) -> Layer:
    """Structurally transform a layer, recursing into compound children.

    ``leaf_fn`` is applied to every non-compound layer; compound layers are
    rebuilt through their ``meta['rebuild']`` protocol with transformed
    children (preserving a post-construction rename, e.g. by :func:`named`).
    Shared by deferred-batch-norm conversion and the mixed-precision policy.
    """
    meta = layer.meta
    if isinstance(meta, dict) and meta.get("kind") == "compound":
        children = meta["children"]
        if isinstance(children, dict):
            new_children: Any = {
                k: map_layer_tree(v, leaf_fn) for k, v in children.items()
            }
            unchanged = all(new_children[k] is children[k] for k in children)
        else:
            new_children = [map_layer_tree(v, leaf_fn) for v in children]
            unchanged = all(n is o for n, o in zip(new_children, children))
        if unchanged:
            return layer
        rebuilt = meta["rebuild"](new_children)
        if rebuilt.name != layer.name:
            # The rebuild closure carries the construction-time name; keep the
            # current (possibly disambiguated) name so partition-time
            # uniqueness checks still hold.
            rebuilt = dataclasses.replace(rebuilt, name=layer.name)
        return rebuilt
    return leaf_fn(layer)


def named(layers: Sequence[Layer]) -> List[Layer]:
    """Disambiguate duplicate layer names by suffixing an index.

    The reference requires children of the wrapped Sequential to be distinct
    objects (reference: torchgpipe/gpipe.py:53-64 ``verify_module``); names
    here play the role of identity.
    """
    used: set = set()
    out: List[Layer] = []
    for layer in layers:
        name = layer.name
        if name in used:
            k = 1
            while f"{layer.name}_{k}" in used:
                k += 1
            name = f"{layer.name}_{k}"
        used.add(name)
        out.append(
            dataclasses.replace(layer, name=name) if name != layer.name else layer
        )
    return out


def chain(sub: Sequence[Layer], name: str = "chain") -> Layer:
    """Compose several layers into one Layer (e.g. one pipeline *stage* of the
    SPMD engine, or a transformer block built from sub-layers).

    Skip connections are supported as long as every (stash, pop) pair resolves
    *within* the chain.
    """
    sub = list(sub)
    unresolved_pops = []
    stashed_names = set()
    for l in sub:
        for k in l.pop:
            if k not in stashed_names:
                unresolved_pops.append(k)
            else:
                stashed_names.discard(k)
        stashed_names.update(l.stash)
    if unresolved_pops:
        raise ValueError(
            f"chain {name!r} has pops with no matching stash inside the chain: "
            f"{unresolved_pops}"
        )
    if stashed_names:
        # The composed Layer declares stash=(), so leftover stashes would be
        # silently dropped instead of routed to a later stage — fail fast.
        raise ValueError(
            f"chain {name!r} has stashes never popped inside the chain: "
            f"{sorted(stashed_names)}; a skip crossing the chain boundary "
            "must be declared on the chain itself (use the flat layer list "
            "with GPipe for cross-stage skips)"
        )

    def init(rng, in_spec):
        params_list, state_list, _ = sequential_init(sub, rng, in_spec)
        return tuple(params_list), tuple(state_list)

    def apply(params, state, x, *, rng=None, train=True):
        if not state:
            # Convention: () means "all sub-layers stateless" — lets callers
            # (e.g. the SPMD engine) thread an empty state.
            state = ((),) * len(sub)
        y, new_states = sequential_apply(
            sub, params, state, x, rng=rng, train=train
        )
        return y, tuple(new_states)

    return Layer(
        name=name,
        init=init,
        apply=apply,
        meta={
            "kind": "compound",
            "children": list(sub),
            "rebuild": lambda new_sub: chain(new_sub, name),
        },
    )


def _infer_layer(
    layer: Layer,
    params: Pytree,
    state: Pytree,
    in_spec: Spec,
    pops_spec: Any,
) -> Tuple[Spec, Spec]:
    """Shape-infer one layer application (skip-aware) via ``eval_shape``."""

    def run(p, s, x, pops):
        key = jax.random.PRNGKey(0)
        if layer.stash or layer.pop:
            y, stashed, _ = layer.apply(p, s, x, pops=pops, rng=key, train=True)
            return y, stashed
        y, _ = layer.apply(p, s, x, rng=key, train=True)
        return y, {}

    x = jax.tree_util.tree_map(lambda sd: jnp.zeros(sd.shape, sd.dtype), in_spec)
    return jax.eval_shape(run, params, state, x, pops_spec)


def _spec_step(
    layer: Layer,
    params: Pytree,
    state: Pytree,
    spec: Spec,
    skip_specs: dict,
) -> Spec:
    """Thread one layer's shape inference (incl. skip-connection specs)."""
    pops_spec = {k: skip_specs.pop(k) for k in layer.pop}
    new_spec, stashed_spec = _infer_layer(layer, params, state, spec, pops_spec)
    skip_specs.update(stashed_spec)
    return new_spec


def sequential_init(
    layers: Sequence[Layer], rng: jax.Array, in_spec: Spec
) -> Tuple[List[Pytree], List[Pytree], List[Spec]]:
    """Initialize every layer, threading shape inference (and skip-connection
    specs) through the chain.

    Returns per-layer ``params``, ``state`` and the list of input specs seen by
    each layer (``specs[i]`` is the input spec of ``layers[i]``; a final entry
    holds the model output spec).
    """
    params_list: List[Pytree] = []
    state_list: List[Pytree] = []
    specs: List[Spec] = [in_spec]
    spec = in_spec
    skip_specs: dict = {}
    for i, layer in enumerate(layers):
        layer_rng = jax.random.fold_in(rng, i)
        p, s = layer.init(layer_rng, spec)
        params_list.append(p)
        state_list.append(s)
        spec = _spec_step(layer, p, s, spec, skip_specs)
        specs.append(spec)
    return params_list, state_list, specs


def sequential_specs(
    layers: Sequence[Layer], in_spec: Spec
) -> List[Spec]:
    """Per-layer input specs of the sequential model, computed abstractly.

    Like :func:`sequential_init` but without materializing any parameters —
    used by the distributed engine so each rank initializes only its own
    partition (``specs[i]`` is the input spec of ``layers[i]``; the final
    entry is the model output spec).
    """
    specs: List[Spec] = [in_spec]
    spec = in_spec
    skip_specs: dict = {}
    for layer in layers:
        p, s = jax.eval_shape(
            lambda r, layer=layer, spec=spec: layer.init(r, spec),
            jax.random.PRNGKey(0),
        )
        spec = _spec_step(layer, p, s, spec, skip_specs)
        specs.append(spec)
    return specs


def apply_layer(
    layer: Layer,
    params: Pytree,
    state: Pytree,
    x: Any,
    skips: dict,
    *,
    rng: Optional[jax.Array] = None,
    train: bool = True,
) -> Tuple[Any, Pytree]:
    """Apply one layer, routing skip stash/pop through the ``skips`` dict
    (mutated in place).  Shared by the sequential oracle, chain, the MPMD
    stage runner, and the profiler, so the dispatch convention cannot drift."""
    if layer.stash or layer.pop:
        pops = {k: skips.pop(k) for k in layer.pop}
        y, stashed, s = layer.apply(
            params, state, x, pops=pops, rng=rng, train=train
        )
        skips.update(stashed)
        return y, s
    return layer.apply(params, state, x, rng=rng, train=train)


def sequential_apply(
    layers: Sequence[Layer],
    params: Sequence[Pytree],
    state: Sequence[Pytree],
    x: Any,
    *,
    rng: Optional[jax.Array] = None,
    train: bool = True,
) -> Tuple[Any, List[Pytree]]:
    """Run the full (un-partitioned) sequential model, including skip
    connections.

    This is the "transparency oracle" path: pipeline outputs must match it
    exactly (reference: tests/test_transparency.py:7-42).
    """
    new_state: List[Pytree] = []
    skips: dict = {}
    for i, layer in enumerate(layers):
        layer_rng = jax.random.fold_in(rng, i) if rng is not None else None
        x, s = apply_layer(
            layer, params[i], state[i], x, skips, rng=layer_rng, train=train
        )
        new_state.append(s)
    return x, new_state
