"""Tensor parallelism primitives (Megatron-style, shard_map-native).

The reference has no tensor parallelism at all (SURVEY.md §2.2: "Tensor
parallelism (TP): ABSENT") — this is new TPU-native capability.  Weights of
a parallel region are sharded over a ``tp`` mesh axis — column-parallel for
the region's input projections (attention heads / MLP hidden units split
across lanes), row-parallel for the output projection — and the activations
entering/leaving the region are replicated.  Two collectives make the math
exact (Shoeybi et al., "Megatron-LM", arXiv:1909.08053 — public technique,
implemented here from the math):

* **region exit** — :func:`psum_value`: ``psum`` of the per-lane partial
  outputs in forward, *identity* in backward.  The downstream computation is
  replicated over tp, so each lane already holds the full output cotangent;
  a raw ``lax.psum`` would transpose to another ``psum`` (shard_map's
  conservative rule when replication checking is off) and over-count every
  gradient upstream of the region by the tp size.
* **region entry** — :func:`psum_grad`: identity in forward, ``psum`` over
  the tp axis in backward.  Each lane back-propagates only its own heads' /
  hidden-units' contribution to the region input; summing the cotangents
  reassembles the full gradient before it reaches the (replicated) layers
  upstream.

With both in place, every activation *outside* a region — and therefore the
gradient of every tp-replicated parameter (norm scales, embeddings, heads) —
is bit-identical across tp lanes; no separate gradient synchronization pass
is needed.  Parameters sharded over tp keep lane-local gradients, which is
exactly the sharding their optimizer state wants.

On TPU hardware the two psums per region ride the ICI mesh; tp should map to
the innermost (fastest) mesh dimension (:func:`torchgpipe_tpu.spmd.make_mesh`
lays it out that way).
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

Pytree = Any


def partition_rules(tp_axis: str, pp_axis: str = "pp") -> Any:
    """The Megatron tensor-parallel layout as an ordered regex →
    PartitionSpec rule table over STACKED block param paths (the
    unified layer of :mod:`torchgpipe_tpu.analysis.partition_rules`).

    Column-parallel projections (attention q/k/v, MLP up/gate) shard
    their OUTPUT dim over tp; row-parallel projections (attention
    output, MLP down) their INPUT dim; per-hidden biases shard with the
    hidden dim; everything else replicates across tp (stage dim over
    pp).  This is the same layout the framework transformer block
    declares structurally (``meta['param_specs']``) — the unified-layer
    tests pin the two resolving identically, so either form is THE
    layout."""
    from torchgpipe_tpu.analysis.partition_rules import (
        PartitionRule,
        RuleTable,
    )

    return RuleTable(
        name=f"tensor-parallel:{tp_axis}",
        rules=(
            PartitionRule(
                r"(^|/)(wq|wk|wv|w_gate|w_up|w_fc|qb|kb|vb)$",
                P(pp_axis, None, tp_axis),
                note="column-parallel: output dim over tp",
            ),
            PartitionRule(
                r"(^|/)(wo|w_down|w_proj|oa)$",
                P(pp_axis, tp_axis, None),
                note="row-parallel: input dim over tp",
            ),
            PartitionRule(
                r"(^|/)(bq|bk|bv|b_fc)$",
                P(pp_axis, tp_axis),
                note="per-hidden biases shard with the hidden dim",
            ),
            PartitionRule(
                r".*",
                P(pp_axis),
                note="norm scales / post-psum biases replicate over tp",
            ),
        ),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def psum_grad(x: Pytree, axis_name: str) -> Pytree:
    """Identity forward; ``psum`` of the cotangent over ``axis_name`` backward.

    Place at the *entry* of a tensor-parallel region (after the last
    replicated computation, before the column-parallel matmuls).  The
    Megatron "f" operator.
    """
    return x


def _psum_grad_fwd(x: Pytree, axis_name: str) -> Tuple[Pytree, None]:
    return x, None


def _psum_grad_bwd(axis_name: str, _: None, g: Pytree) -> Tuple[Pytree]:
    return (jax.tree_util.tree_map(lambda t: lax.psum(t, axis_name), g),)


psum_grad.defvjp(_psum_grad_fwd, _psum_grad_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def psum_value(x: Pytree, axis_name: str) -> Pytree:
    """``psum`` over ``axis_name`` forward; identity backward.

    Place at the *exit* of a tensor-parallel region (after the row-parallel
    matmul) to sum the per-lane partial outputs.  The Megatron "g" operator:
    since everything downstream is replicated over the axis, the local
    cotangent already equals the full one — transposing to another psum
    would multiply gradients by the lane count.
    """
    return jax.tree_util.tree_map(lambda t: lax.psum(t, axis_name), x)


def _psum_value_fwd(x: Pytree, axis_name: str) -> Tuple[Pytree, None]:
    return psum_value(x, axis_name), None


def _psum_value_bwd(axis_name: str, _: None, g: Pytree) -> Tuple[Pytree]:
    return (g,)


psum_value.defvjp(_psum_value_fwd, _psum_value_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def pmax_stop(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """``pmax`` over ``axis_name`` with zero gradient.

    For numerical-stability maxima (log-sum-exp shifts) whose analytic
    gradient contribution cancels: ``lax.pmax`` has no differentiation rule,
    and wrapping it in ``stop_gradient`` does not keep autodiff tracing from
    reaching the primitive — this does.
    """
    return lax.pmax(x, axis_name)


def _pmax_stop_fwd(
    x: jnp.ndarray, axis_name: str
) -> Tuple[jnp.ndarray, None]:
    return pmax_stop(x, axis_name), None


def _pmax_stop_bwd(axis_name: str, _: None, g: Pytree) -> Tuple[jnp.ndarray]:
    return (jax.tree_util.tree_map(lambda t: t * 0, g),)


pmax_stop.defvjp(_pmax_stop_fwd, _pmax_stop_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def all_gather_value(
    x: jnp.ndarray, axis_name: str, axis: int = -1
) -> jnp.ndarray:
    """``all_gather`` shards along ``axis`` forward; *slice* backward.

    Forward: every lane receives the full array (lane shards concatenated
    along ``axis`` in lane order).  Backward: each lane keeps only its own
    shard's slice of the (replicated) cotangent.  Like :func:`psum_value`
    this pins the transpose for replicated-downstream use: JAX's default
    all_gather transpose is a reduce-scatter, which sums the identical
    per-lane cotangents and over-counts by the lane count.

    Used by the vocab-parallel LM head to re-assemble full-vocabulary logits
    (``lm_head(..., gather_logits=True)``).
    """
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)


def _all_gather_value_fwd(
    x: Pytree,
    axis_name: str,
    axis: int,
) -> Tuple[jnp.ndarray, int]:
    return all_gather_value(x, axis_name, axis), x.shape[axis % x.ndim]


def _all_gather_value_bwd(
    axis_name: str,
    axis: int,
    local_size: int,
    g: Pytree,
) -> Tuple[jnp.ndarray]:
    lane = lax.axis_index(axis_name)
    ax = axis % g.ndim
    return (
        lax.dynamic_slice_in_dim(g, lane * local_size, local_size, ax),
    )


all_gather_value.defvjp(_all_gather_value_fwd, _all_gather_value_bwd)
