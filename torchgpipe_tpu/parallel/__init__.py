"""Parallelism building blocks beyond the pipeline engines.

Long-context / sequence-context parallelism is first-class in this framework
(the reference predates it — SURVEY.md §5 "long-context: ABSENT"; this is new
TPU-native capability, not ported behavior): ring attention over an ``sp``
mesh axis composes with the SPMD pipeline's ``pp`` and ``dp`` axes in one
compiled program.
"""

from torchgpipe_tpu.parallel.interleaved import (  # noqa: F401
    InterleavedTables,
    interleaved_forward_tables,
    interleaved_tables,
)
from torchgpipe_tpu.parallel.ring_attention import (  # noqa: F401
    attention,
    full_attention,
    ring_attention,
)
from torchgpipe_tpu.parallel.ulysses import (  # noqa: F401
    ulysses_attention,
)
from torchgpipe_tpu.parallel.zerobubble import (  # noqa: F401
    ZeroBubbleTables,
    zero_bubble_tables,
)
