"""Interleaved-1F1B (virtual pipeline stages) schedule tables.

Megatron-style interleaved pipelining: each of the ``n`` devices owns ``v``
non-adjacent model chunks (device ``j`` holds global blocks ``c*n + j`` for
``c in range(v)``), so a micro-batch visits every device ``v`` times.  The
fill/drain bubble shrinks by ~``v`` because a device starts computing chunk
0 of micro-batch 0 after ``j`` hops instead of waiting for a ``v``-deep
stage to finish.

The schedule here is *static*: :func:`interleaved_tables` runs a lockstep
list-scheduling simulation in Python (each device executes its cells in the
canonical Megatron order, stalling until data dependencies are satisfied)
and returns dense per-tick tables the SPMD engine scans over.  Hand-offs
ride one forward and one backward ``ppermute`` per tick; a receiver
classifies the incoming value by looking at the *sender's* table row for
the previous tick, so the tables are the single source of truth for both
compute and routing.

No reference counterpart: the reference implements fill-drain only
(reference: torchgpipe/pipeline.py:49-65).  Schedule shape follows
Narayanan et al., "Efficient Large-Scale Language Model Training on GPU
Clusters Using Megatron-LM" (arXiv:2104.04473) §2.2.

Conventions
-----------
* ``kind``: 0 = forward, 1 = backward, 2 = idle.
* ``chunk``: local chunk index ``c`` (global block = ``c*n + j``).
* ``mb``: micro-batch index ``i``.
* Tables are ``[T, n]`` so the scan can feed tick rows as xs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

FWD, BWD, IDLE = 0, 1, 2


def _cell_sequence(n: int, m: int, v: int, j: int) -> List[Tuple[int, int, int]]:
    """Device ``j``'s cell order: warmup forwards, 1F1B steady state, drain.

    Forward cells are enumerated in Megatron order (micro-batches in groups
    of ``n``; the chunk index advances after each group), backwards in the
    mirror order with chunks reversed.
    """
    total = m * v

    def fwd_cell(k: int) -> Tuple[int, int, int]:
        chunk = (k // n) % v
        mb = (k // (n * v)) * n + k % n
        return (FWD, chunk, mb)

    def bwd_cell(k: int) -> Tuple[int, int, int]:
        chunk = v - 1 - ((k // n) % v)
        mb = (k // (n * v)) * n + k % n
        return (BWD, chunk, mb)

    if v == 1:
        warmup = min(n - j - 1, total)  # classic 1F1B depth
    else:
        warmup = min((n - j - 1) * 2 + (v - 1) * n, total)
    seq: List[Tuple[int, int, int]] = [fwd_cell(k) for k in range(warmup)]
    f, b = warmup, 0
    while f < total:
        seq.append(fwd_cell(f)); f += 1
        seq.append(bwd_cell(b)); b += 1
    while b < total:
        seq.append(bwd_cell(b)); b += 1
    return seq


def _producer(
    n: int,
    v: int,
    kind: int,
    c: int,
    i: int,
    j: int,
) -> Optional[Tuple[int, int, int, int]]:
    """The cell whose output this cell consumes, or None for an external
    input (forward chunk 0 stage 0) / the local loss seed (backward chunk
    v-1 stage n-1, which also depends on its own forward — handled by the
    caller as a same-device dependency)."""
    if kind == FWD:
        if j > 0:
            return (FWD, c, i, j - 1)
        if c > 0:
            return (FWD, c - 1, i, n - 1)
        return None
    if j < n - 1:
        return (BWD, c, i, j + 1)
    if c < v - 1:
        return (BWD, c + 1, i, 0)
    return None


@dataclass(frozen=True)
class InterleavedTables:
    """Static schedule tables plus the buffer geometry the engine needs."""

    n: int
    m: int
    v: int
    ticks: int
    kind: np.ndarray   # [T, n] int32
    chunk: np.ndarray  # [T, n] int32
    mb: np.ndarray     # [T, n] int32
    slots: int         # inbox/saved-input ring-buffer depth (per chunk)

    @property
    def bubble_ticks(self) -> int:
        return self.ticks - 2 * self.m * self.v


def _check_args(n: int, m: int, v: int) -> None:
    if n < 1 or v < 1 or m < 1:
        raise ValueError(f"need n, m, v >= 1, got n={n} m={m} v={v}")
    if v > 1 and m % n != 0:
        raise ValueError(
            f"interleaved schedule needs chunks (m={m}) divisible by the "
            f"pipeline depth (n={n}) — Megatron's micro-batch grouping "
            "(arXiv:2104.04473 §2.2) assumes full groups"
        )


def _lockstep_simulate(
    n: int,
    v: int,
    seqs: List[List[Tuple[int, int, int]]],
) -> Tuple[List[List[int]], List[List[int]], List[List[int]]]:
    """Lockstep list-scheduling of per-device cell sequences into rows.

    Each tick, every device attempts its next cell; a cell runs only if
    its producer ran at a *strictly earlier* tick (hand-offs take one
    ppermute tick; same-device dependencies also resolve tick-to-tick).
    The simulation terminates — each tick at least the globally-earliest
    unsatisfied cell's producer chain makes progress.
    """
    pos = [0] * n
    done: dict = {}  # (kind, c, i, j) -> tick
    rows_kind: List[List[int]] = []
    rows_chunk: List[List[int]] = []
    rows_mb: List[List[int]] = []
    t = 0
    total = sum(len(s) for s in seqs)
    limit = 4 * total + 4 * n * v + 64  # far above any valid schedule
    while any(pos[j] < len(seqs[j]) for j in range(n)):
        if t > limit:
            raise RuntimeError(
                f"schedule did not converge (n={n} v={v}, {total} cells)"
            )
        krow, crow, irow = [IDLE] * n, [0] * n, [0] * n
        fired = []
        for j in range(n):
            if pos[j] >= len(seqs[j]):
                continue
            kind, c, i = seqs[j][pos[j]]
            dep = _producer(n, v, kind, c, i, j)
            ok = dep is None or done.get(dep, t) < t
            if kind == BWD and c == v - 1 and j == n - 1:
                # Loss seed: needs this device's own forward of the same
                # cell at an earlier tick.
                ok = ok and done.get((FWD, c, i, j), t) < t
            if ok:
                krow[j], crow[j], irow[j] = kind, c, i
                fired.append((kind, c, i, j))
                pos[j] += 1
        # Commit AFTER scanning all devices: cells fired this tick must not
        # satisfy same-tick dependencies.
        for cell in fired:
            done[cell] = t
        rows_kind.append(krow); rows_chunk.append(crow); rows_mb.append(irow)
        t += 1
    return rows_kind, rows_chunk, rows_mb, t


def interleaved_tables(n: int, m: int, v: int) -> InterleavedTables:
    """Lockstep-simulate the interleaved training schedule into dense
    tables; the result is checked for validity before returning."""
    _check_args(n, m, v)
    seqs = [_cell_sequence(n, m, v, j) for j in range(n)]
    rows_kind, rows_chunk, rows_mb, t = _lockstep_simulate(n, v, seqs)

    tables = InterleavedTables(
        n=n, m=m, v=v, ticks=t,
        kind=np.asarray(rows_kind, np.int32),
        chunk=np.asarray(rows_chunk, np.int32),
        mb=np.asarray(rows_mb, np.int32),
        slots=_required_slots(n, v, rows_kind, rows_chunk, rows_mb),
    )
    _validate(tables)
    return tables


def interleaved_forward_tables(n: int, m: int, v: int) -> InterleavedTables:
    """Forward-only tables for pipelined inference over virtual stages.

    Same lockstep simulation, but each device's sequence is just its
    ``m * v`` forward cells in Megatron order — a fill-drain schedule over
    the ``n * v`` virtual stages with round-robin device mapping.
    """
    _check_args(n, m, v)
    seqs = [
        [cell for cell in _cell_sequence(n, m, v, j) if cell[0] == FWD]
        for j in range(n)
    ]
    rows_kind, rows_chunk, rows_mb, t = _lockstep_simulate(n, v, seqs)
    # Slot depth: activation liveness only (delivery tick -> consumption;
    # no backward cells, so each span ends at the cell's own tick).
    fwd_tick, bwd_tick = _cell_ticks(n, rows_kind, rows_chunk, rows_mb)
    tables = InterleavedTables(
        n=n, m=m, v=v, ticks=t,
        kind=np.asarray(rows_kind, np.int32),
        chunk=np.asarray(rows_chunk, np.int32),
        mb=np.asarray(rows_mb, np.int32),
        slots=_min_slot_depth([_act_spans(n, v, fwd_tick, bwd_tick)]),
    )
    _validate(tables, forward_only=True)
    return tables


def _min_slot_depth(span_families: Dict) -> int:
    """Smallest power-of-two ring-buffer depth S such that, within every
    family, slot ``(device, chunk, mb % S)`` never holds two live values at
    once (liveness intervals keyed ``(j, c, i) -> (start_tick, end_tick)``,
    inclusive).  Raises rather than returning an unverified depth."""

    def fits(spans, s) -> bool:
        by_slot: dict = {}
        for (j, c, i), span in spans.items():
            by_slot.setdefault((j, c, i % s), []).append(span)
        for intervals in by_slot.values():
            intervals.sort()
            for a, b in zip(intervals, intervals[1:]):
                if b[0] <= a[1]:
                    return False
        return True

    for s in (1 << p for p in range(0, 16)):
        if all(fits(spans, s) for spans in span_families):
            return s
    raise RuntimeError("no feasible slot count found")


def _cell_ticks(
    n: int,
    rows_kind: List[List[int]],
    rows_chunk: List[List[int]],
    rows_mb: List[List[int]],
) -> Tuple[Dict, Dict]:
    """Per-cell fire ticks: ``({(j,c,i): fwd_tick}, {(j,c,i): bwd_tick})``."""
    fwd_tick: dict = {}
    bwd_tick: dict = {}
    for t, (krow, crow, irow) in enumerate(zip(rows_kind, rows_chunk, rows_mb)):
        for j in range(n):
            key = (j, crow[j], irow[j])
            if krow[j] == FWD:
                fwd_tick[key] = t
            elif krow[j] == BWD:
                bwd_tick[key] = t
    return fwd_tick, bwd_tick


def _act_spans(n: int, v: int, fwd_tick: Dict, bwd_tick: Dict) -> dict:
    """Activation inbox / saved-input liveness: from the producer's forward
    tick + 1 (the ppermute delivery; the cell's own tick when there is no
    producer) until the matching backward cell reads it (its own forward
    tick when the schedule has no backwards)."""
    spans: dict = {}
    for (j, c, i), tf in fwd_tick.items():
        dep = _producer(n, v, FWD, c, i, j)
        start = tf if dep is None else fwd_tick[(dep[3], dep[1], dep[2])] + 1
        spans[(j, c, i)] = (start, bwd_tick.get((j, c, i), tf))
    return spans


def _required_slots(
    n: int,
    v: int,
    rows_kind: List[List[int]],
    rows_chunk: List[List[int]],
    rows_mb: List[List[int]],
) -> int:
    """Slot depth for the training schedule: activation spans plus the
    cotangent-inbox spans (producer's backward tick + 1 until the consuming
    backward cell's tick)."""
    fwd_tick, bwd_tick = _cell_ticks(n, rows_kind, rows_chunk, rows_mb)
    cot_spans: dict = {}
    for (j, c, i), tb in bwd_tick.items():
        dep = _producer(n, v, BWD, c, i, j)
        if dep is not None:
            cot_spans[(j, c, i)] = (bwd_tick[(dep[3], dep[1], dep[2])] + 1, tb)
    return _min_slot_depth(
        [_act_spans(n, v, fwd_tick, bwd_tick), cot_spans]
    )


def _validate(tb: InterleavedTables, forward_only: bool = False) -> None:
    """Every cell exactly once per device; dependencies strictly ordered."""
    n, m, v = tb.n, tb.m, tb.v
    done: dict = {}
    for t in range(tb.ticks):
        for j in range(n):
            k = int(tb.kind[t, j])
            if k == IDLE:
                continue
            if forward_only and k != FWD:
                raise AssertionError(f"non-forward cell in forward tables")
            cell = (k, int(tb.chunk[t, j]), int(tb.mb[t, j]), j)
            if cell in done:
                raise AssertionError(f"cell {cell} scheduled twice")
            dep = _producer(n, v, *cell)
            if dep is not None and not (done.get(dep, t) < t):
                raise AssertionError(f"{cell} at tick {t} before dep {dep}")
            if k == BWD and cell[1] == v - 1 and j == n - 1:
                if not done.get((FWD, cell[1], cell[2], j), t) < t:
                    raise AssertionError(f"loss cell {cell} before own fwd")
            done[cell] = t
    expect = (1 if forward_only else 2) * m * v * n
    if len(done) != expect:
        raise AssertionError(f"{len(done)} cells scheduled, want {expect}")
