"""Ulysses-style sequence parallelism: all_to_all head/sequence swap.

DeepSpeed-Ulysses (Jacobs et al., arXiv:2309.14509 — public technique,
implemented here from the paper's math): with the sequence sharded over the
``sp`` mesh axis, one ``all_to_all`` re-shards the attention inputs from
sequence-split to HEAD-split, so every lane computes ordinary full-sequence
attention for ``h/sp`` of the heads; a final ``all_to_all`` swaps the
output back to sequence-split.  Communication is FOUR all_to_alls per
attention call — q, k, v inbound (k/v at ``g`` kv heads, so
O(b·s·(2h+2g)·d/sp) bytes total per lane) and the output back — riding
ICI, independent of sequence length, vs the ring's ``sp - 1`` neighbor
steps of K/V blocks — and the local compute is
a plain dense/flash attention over the whole sequence, so the Pallas
flash kernel applies as-is (the ring's blockwise online-softmax path
cannot use it per-step).

Trade-off vs ring attention (:mod:`torchgpipe_tpu.parallel.ring_attention`):
Ulysses needs ``n_heads % sp == 0`` (it shards heads) and materializes the
full-length sequence per lane during attention (memory O(s), not O(s/sp)),
so the ring remains the choice for extreme lengths; Ulysses wins at
moderate lengths where head count, not memory, is the binding constraint.
Select per model with ``TransformerConfig(sp_impl="ulysses")``.

The reference has no sequence parallelism of any kind (SURVEY.md §2.2
lists ring/Ulysses as absent) — this module is TPU-native new capability.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def partition_rules(sp_axis: str, pp_axis: str = "pp") -> Any:
    """Ulysses' param layout as a rule table (the unified layer of
    :mod:`torchgpipe_tpu.analysis.partition_rules`): sequence
    parallelism shards ACTIVATIONS (the sequence dim, swapped to heads
    around attention), never parameters — every param leaf replicates
    over ``sp`` (stage dim over ``pp``).  Emitted so the static
    sharding verifier can certify an sp layout by the same resolution
    path as tp/ep ones."""
    from torchgpipe_tpu.analysis.partition_rules import (
        PartitionRule,
        RuleTable,
    )

    del sp_axis  # declared for symmetry: no param leaf mentions it
    return RuleTable(
        name="ulysses-sequence-parallel",
        rules=(
            PartitionRule(
                r".*", P(pp_axis),
                note="sp shards activations, not params",
            ),
        ),
    )


def _swap_to_heads(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """[b, s_loc, h, d] -> [b, s, h/sp, d]: shard heads, gather sequence."""
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)


def _swap_to_seq(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """[b, s, h/sp, d] -> [b, s_loc, h, d]: gather heads, shard sequence."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Full-sequence attention over a sequence-sharded batch via two
    all_to_alls.

    ``q``: ``[b, s_loc, h, d]``; ``k``/``v``: ``[b, s_loc, g, d]`` with
    ``g`` dividing ``h`` (GQA) — the same convention as
    :func:`torchgpipe_tpu.parallel.ring_attention.ring_attention`.  Both
    ``h`` and ``g`` must divide by the sp axis size: heads are what gets
    sharded during the compute.  The head split is contiguous, so the
    GQA pairing (query head ``i`` -> kv head ``i // (h/g)``) is preserved
    lane-locally: lane ``l`` holds q heads ``[l·h/sp, (l+1)·h/sp)`` and kv
    heads ``[l·g/sp, (l+1)·g/sp)``, and ``(l·h/sp + j) // (h/g)`` lands in
    exactly that kv range.

    Gradients flow through the all_to_alls' own transposes (an all_to_all
    with split/concat swapped), so no custom vjp is needed.
    """
    sp = lax.psum(1, axis_name)
    h, g = q.shape[2], k.shape[2]
    if h % sp != 0 or g % sp != 0:
        raise ValueError(
            f"Ulysses sequence parallelism shards attention heads: n_heads "
            f"({h}) and kv_heads ({g}) must both be divisible by the "
            f"{axis_name!r} axis size ({sp}); use sp_impl='ring' (ring "
            "attention shards the sequence, not heads) for this head count"
        )
    from torchgpipe_tpu.parallel.ring_attention import attention

    qh = _swap_to_heads(q, axis_name)
    kh = _swap_to_heads(k, axis_name)
    vh = _swap_to_heads(v, axis_name)
    # Local full-sequence attention on h/sp heads: the normal non-sp
    # dispatch applies (Pallas flash kernel on TPU when shapes allow).
    out = attention(qh, kh, vh, axis_name=None, causal=causal,
                    sm_scale=sm_scale, window=window)
    return _swap_to_seq(out, axis_name)
