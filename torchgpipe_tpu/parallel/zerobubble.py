"""Zero-bubble (ZB-H1-style) pipeline schedule tables.

The classic backward cell does two jobs at once: the ACTIVATION gradient
(dx — on the critical path, the downstream stage is waiting for it) and
the WEIGHT gradient (dW — consumed only by the optimizer at step end).
Zero-bubble schedules (Qi et al., "Zero Bubble Pipeline Parallelism",
arXiv:2401.10241 — public technique, implemented here from the paper's
idea with our own greedy scheduler) split them: ``B`` cells compute only
dx and hand the cotangent downstream immediately; ``W`` cells compute dW
afterwards, turning ticks 1F1B would leave idle into useful work (the
drain tail of early stages in particular).  Per-tick work drops from
``max(t_F, t_B + t_W)`` to ``max(t_F, t_B, t_W)`` — for a transformer
block, roughly one matmul per tick instead of two on backward ticks — and
the fill/drain bubble is back-filled with useful W work.

Like :mod:`torchgpipe_tpu.parallel.interleaved`, the schedule is a STATIC
table produced by lockstep list-scheduling in Python and scanned over by
the engine: per stage the F/B order is exactly classic 1F1B (so the
in-flight activation bound n - j is preserved), with each micro-batch's
W placed immediately after its B (the H1-style memory-bounded choice —
residuals and stored cotangents stay within the 1F1B window; see
``_zb_sequence``).  Early stages' drain tail is thereby W-filled; warmup
stalls of late stages remain idle (they have no W work yet — ZB-2-style
deferral could fill them at the cost of O(m) residual memory, the trade
this module deliberately does not take).  The table generator also
proves the buffer geometry: ring-slot
depths for the activation/cotangent inboxes, the stored-vjp residuals
(live F → W), and the stored cotangents (live B → W), each validated
collision-free.

No reference counterpart at any level: the reference has fill-drain only
(reference: torchgpipe/pipeline.py:49-65; SURVEY.md §2.2).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

F, B, W, IDLE = 0, 1, 2, 3


def _zb_sequence(n: int, m: int, j: int) -> List[Tuple[int, int]]:
    """Stage ``j``'s ZB op order: classic 1F1B warmup and F/B cadence,
    with each micro-batch's W immediately after its B.

    The immediate-W placement is the memory-bounded (H1-style) choice:
    the stored-vjp residuals (live F → W) and stored cotangents (live
    B → W) stay within the 1F1B in-flight window instead of piling up to
    O(m), while the split still halves the per-tick backward cost and the
    early stages' drain tail is W-filled rather than idle."""
    warmup = min(n - j - 1, m)
    seq: List[Tuple[int, int]] = [(F, i) for i in range(warmup)]
    f, b = warmup, 0
    while f < m:
        seq.append((F, f)); f += 1
        seq.append((B, b)); seq.append((W, b)); b += 1
    while b < m:
        seq.append((B, b)); seq.append((W, b)); b += 1
    return seq


def _dep(n: int, kind: int, i: int, j: int) -> Optional[Tuple[int, int, int]]:
    """The remote cell this cell consumes, or None (external input /
    same-stage dependencies handled by the caller)."""
    if kind == F:
        return (F, i, j - 1) if j > 0 else None
    if kind == B:
        return (B, i, j + 1) if j < n - 1 else None
    return None  # W depends on the SAME stage's B — checked separately


@dataclass(frozen=True)
class ZeroBubbleTables:
    """Static ZB schedule plus the proven buffer geometry."""

    n: int
    m: int
    ticks: int
    kind: np.ndarray       # [T, n] int32 in {F, B, W, IDLE}
    mb: np.ndarray         # [T, n] int32
    slots: int             # act/cotangent inbox ring depth (i % slots)
    y_slots: int           # last-stage loss-seed ring depth (F -> B span)
    resid_slots: int       # stored-vjp residual ring depth (F -> W span)
    dy_slots: int          # stored-cotangent ring depth (B -> W span)
    x_slots: int           # stored cell-INPUT ring depth (F -> B span) —
                           # the recompute variant (checkpoint='always')
                           # stores inputs instead of F-time vjp residuals

    @property
    def bubble_ticks(self) -> int:
        return self.ticks * self.n - 3 * self.m * self.n  # idle cells

    def weighted_makespan(self, t_f: float, t_b: float, t_w: float) -> float:
        """Lockstep makespan with per-op costs (each tick costs the max
        over the stages' ops that tick) — the number the schedule exists
        to minimize."""
        cost = {F: t_f, B: t_b, W: t_w, IDLE: 0.0}
        return float(
            sum(
                max(cost[int(k)] for k in row)
                for row in self.kind
            )
        )


def fused_1f1b_weighted_makespan(
    n: int, m: int, t_f: float = 1.0, t_bw: float = 2.0
) -> float:
    """Exact lockstep cost of classic 1F1B with a FUSED backward (dx+dW in
    one cell costing ``t_bw``), from the engine's closed-form tick
    predicates (spmd.py ``_build_train_step_1f1b``).

    The comparator for :meth:`ZeroBubbleTables.weighted_makespan`: the
    >=1.2x zb win band (tests/test_zerobubble.py) is this figure over the
    zb makespan at uniform split costs ``(t_f, t_bw/2, t_bw/2)``; the
    ratio is cost-profile-dependent, so benchmark drivers should evaluate
    it at their CALIBRATED costs (benchmarks/zb_timing.py)."""
    total = 0.0
    for t in range(2 * (m + n - 1)):
        c = 0.0
        for j in range(n):
            tj = t - j
            warm = 0 <= tj <= n - 1 - j and tj < m
            i_s = tj // 2 if tj >= 0 else 0
            steady = tj >= 0 and tj % 2 == 0 and i_s > n - 1 - j and i_s < m
            num = t + j - (2 * n - 1)
            do_b = num >= 0 and num % 2 == 0 and num // 2 < m
            if do_b:
                c = max(c, t_bw)
            elif warm or steady:
                c = max(c, t_f)
        total += c
    return total


def _min_depth(spans: dict) -> int:
    """Smallest power-of-two depth S such that slot ``(j, i % S)`` never
    holds two live values at once (inclusive tick intervals)."""

    def fits(s: int) -> bool:
        by_slot: dict = {}
        for (j, i), span in spans.items():
            by_slot.setdefault((j, i % s), []).append(span)
        for intervals in by_slot.values():
            intervals.sort()
            for a, b in zip(intervals, intervals[1:]):
                if b[0] <= a[1]:
                    return False
        return True

    for s in (1 << p for p in range(0, 16)):
        if fits(s):
            return s
    raise RuntimeError("no feasible slot depth found")


def zero_bubble_tables(n: int, m: int) -> ZeroBubbleTables:
    """Greedy lockstep scheduling of the split-backward schedule; the
    result is validated (every op exactly once, dependencies strictly
    ordered, buffer slots collision-free) before returning."""
    if n < 1 or m < 1:
        raise ValueError(f"need n, m >= 1, got n={n} m={m}")
    seqs = [_zb_sequence(n, m, j) for j in range(n)]
    pos = [0] * n
    done: dict = {}  # (kind, i, j) -> tick
    rows_kind: List[List[int]] = []
    rows_mb: List[List[int]] = []
    t = 0
    limit = 8 * m * n + 8 * n + 64
    while any(pos[j] < len(seqs[j]) for j in range(n)):
        if t > limit:
            raise RuntimeError(f"zb schedule did not converge (n={n} m={m})")
        krow, irow = [IDLE] * n, [0] * n
        fired = []
        for j in range(n):
            if pos[j] >= len(seqs[j]):
                continue
            kind, i = seqs[j][pos[j]]
            dep = _dep(n, kind, i, j)
            ok = dep is None or done.get(dep, t) < t
            if kind == B and j == n - 1:
                # Loss seed: this stage's own forward, earlier tick.
                ok = ok and done.get((F, i, j), t) < t
            if kind == W:
                # Same-stage split: W replays the residuals B touched and
                # the cotangent B stored — strictly after B's tick.
                ok = done.get((B, i, j), t) < t
            if ok:
                krow[j], irow[j] = kind, i
                fired.append((kind, i, j))
                pos[j] += 1
        for cell in fired:
            done[cell] = t
        rows_kind.append(krow)
        rows_mb.append(irow)
        t += 1

    # ---- spans -> proven buffer depths -------------------------------- #
    tick_of: dict = {}
    for tt, (krow, irow) in enumerate(zip(rows_kind, rows_mb)):
        for j in range(n):
            if krow[j] != IDLE:
                tick_of[(krow[j], irow[j], j)] = tt
    act_spans: dict = {}   # delivered act -> F reads it
    cot_spans: dict = {}   # delivered cotangent -> B reads it
    y_spans: dict = {}     # last-stage F output -> B loss seed
    resid_spans: dict = {}  # F stores vjp residuals -> W last read
    dy_spans: dict = {}    # B stores its cotangent -> W reads it
    x_spans: dict = {}     # F stores its input -> B recomputes from it
    for (kind, i, j), tt in tick_of.items():
        if kind == F:
            if j > 0:
                act_spans[(j, i)] = (tick_of[(F, i, j - 1)] + 1, tt)
            if j == n - 1:
                y_spans[(j, i)] = (tt, tick_of[(B, i, j)])
            resid_spans[(j, i)] = (tt, tick_of[(W, i, j)])
            x_spans[(j, i)] = (tt, tick_of[(B, i, j)])
        elif kind == B:
            if j < n - 1:
                cot_spans[(j, i)] = (tick_of[(B, i, j + 1)] + 1, tt)
            dy_spans[(j, i)] = (tt, tick_of[(W, i, j)])
    tables = ZeroBubbleTables(
        n=n, m=m, ticks=t,
        kind=np.asarray(rows_kind, np.int32),
        mb=np.asarray(rows_mb, np.int32),
        # The activation and cotangent spans share one slot array; tag the
        # merged keys structurally so stage j's cotangents can never alias
        # stage j's activations, whatever n is.
        slots=_min_depth({
            **{(("act", j), i): s for (j, i), s in act_spans.items()},
            **{(("cot", j), i): s for (j, i), s in cot_spans.items()},
        }),
        y_slots=_min_depth(y_spans) if y_spans else 1,
        resid_slots=_min_depth(resid_spans),
        dy_slots=_min_depth(dy_spans),
        x_slots=_min_depth(x_spans),
    )
    _validate(tables)
    return tables


def _validate(tb: ZeroBubbleTables) -> None:
    n, m = tb.n, tb.m
    done: dict = {}
    counts = {F: 0, B: 0, W: 0}
    for t in range(tb.ticks):
        for j in range(n):
            k = int(tb.kind[t, j])
            if k == IDLE:
                continue
            cell = (k, int(tb.mb[t, j]), j)
            if cell in done:
                raise AssertionError(f"cell {cell} scheduled twice")
            dep = _dep(n, k, cell[1], j)
            if dep is not None and not done.get(dep, t) < t:
                raise AssertionError(f"{cell} at {t} before dep {dep}")
            if k == B and j == n - 1:
                if not done.get((F, cell[1], j), t) < t:
                    raise AssertionError(f"{cell} before its loss-seed fwd")
            if k == W:
                if not done.get((B, cell[1], j), t) < t:
                    raise AssertionError(f"{cell} before its B")
            done[cell] = t
            counts[k] += 1
    if not (counts[F] == counts[B] == counts[W] == n * m):
        raise AssertionError(f"op counts wrong: {counts} for n={n} m={m}")
