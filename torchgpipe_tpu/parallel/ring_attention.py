"""Ring attention: exact attention over sequence shards on a device ring.

Long sequences are sharded over an ``sp`` mesh axis; each device holds
``[b, s/sp, h, d]`` of Q, K, V.  K/V blocks rotate around the ring via
``lax.ppermute`` (neighbor ICI transfers on TPU) while each device
accumulates its queries' attention with the streaming (online-softmax)
recurrence — numerically exact, never materializing the full ``[s, s]``
score matrix.  Each ring step is ``jax.checkpoint``-ed, so backward
recomputes one block at a time: activation memory is O(s/sp) per device,
which is what makes million-token contexts feasible (Liu et al., "Ring
Attention with Blockwise Transformers", arXiv:2310.01889 — public
technique, implemented here from the math).

The reference framework has no sequence/context parallelism at all
(SURVEY.md §5); this module is the TPU-native new capability that composes
with the pipeline (``pp``) and data (``dp``) axes in
:class:`~torchgpipe_tpu.spmd.SpmdGPipe`.

Differentiable end-to-end: the ``ppermute`` transposes route K/V cotangents
backwards around the ring automatically.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def partition_rules(sp_axis: str, pp_axis: str = "pp") -> Any:
    """Ring attention's param layout as a rule table (the unified layer
    of :mod:`torchgpipe_tpu.analysis.partition_rules`): like Ulysses,
    the ring shards the SEQUENCE (K/V blocks rotate over ``sp``), never
    parameters — every param leaf replicates over ``sp`` (stage dim
    over ``pp``)."""
    from torchgpipe_tpu.analysis.partition_rules import (
        PartitionRule,
        RuleTable,
    )

    del sp_axis  # declared for symmetry: no param leaf mentions it
    return RuleTable(
        name="ring-attention-sequence-parallel",
        rules=(
            PartitionRule(
                r".*", P(pp_axis),
                note="sp shards activations, not params",
            ),
        ),
    )

_NEG = -1e30  # large negative instead of -inf: keeps grads NaN-free


def _group(q: jnp.ndarray, n_kv: int) -> jnp.ndarray:
    """[b, s, h, d] -> [b, s, g, r, d] with h = g*r grouped onto kv heads.

    GQA support at the compute site: K/V stay at their n_kv heads (so the
    ring only moves n_kv-head blocks) and queries are grouped to match.
    Query head ``h`` maps to kv head ``h // r`` — the same pairing as
    ``jnp.repeat(k, r, axis=2)``.
    """
    b, s, h, d = q.shape
    return q.reshape(b, s, n_kv, h // n_kv, d)


def _scores(q: jnp.ndarray, k: jnp.ndarray, sm_scale: float) -> jnp.ndarray:
    # q [b, sq, h, d] x k [b, sk, g, d] (g divides h) -> [b, h, sq, sk];
    # f32 accumulation on the MXU (inputs may be bf16).
    g = k.shape[2]
    qg = _group(q, g)
    s = jnp.einsum(
        "bqgrd,bkgd->bgrqk", qg, k, preferred_element_type=jnp.float32
    ) * sm_scale
    b, _, r, sq, sk = s.shape
    return s.reshape(b, g * r, sq, sk)


def _weighted_v(p: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    # p [b, h, sq, sk] x v [b, sk, g, d] -> [b, h, sq, d]
    b, h, sq, sk = p.shape
    g = v.shape[2]
    pg = p.reshape(b, g, h // g, sq, sk)
    o = jnp.einsum(
        "bgrqk,bkgd->bgrqd", pg, v, preferred_element_type=jnp.float32
    )
    return o.reshape(b, h, sq, v.shape[-1])


def full_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    window: Optional[int] = None,
    seg: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Plain dense attention (single-device oracle / sp-disabled path).

    ``q``: ``[b, s, h, d]``; ``k, v``: ``[b, s, g, d]`` with ``g`` dividing
    ``h`` (grouped-query attention; ``g == h`` is plain MHA).  Returns
    ``[b, s, h, d]``.  ``window`` (requires ``causal``) keeps only the
    last ``window`` positions: attend iff ``0 <= qpos - kpos < window``
    (Mistral-style sliding-window attention).

    ``seg`` (``[b, s]`` int segment ids, 0 = pad) folds the SEQUENCE-
    PACKING mask in: position ``i`` attends ``j`` only when
    ``seg[i] == seg[j]`` — the block-diagonal term that keeps packed
    documents from attending each other
    (:func:`torchgpipe_tpu.utils.data.pack_documents`).  All-masked pad
    rows soften to a uniform distribution (``_NEG``, not ``-inf``), so
    their garbage outputs stay finite; the packed loss weights them out.
    """
    from torchgpipe_tpu.ops.flash_attention import _validate_window

    d = q.shape[-1]
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale
    _validate_window(causal, window)
    s = _scores(q, k, sm_scale)
    sq, sk = q.shape[1], k.shape[1]
    mask = None
    if causal:
        diff = jnp.arange(sq)[:, None] - jnp.arange(sk)[None, :]
        mask = diff >= 0
        if window is not None:
            mask = mask & (diff < window)
        mask = mask[None]  # [1, sq, sk]
    if seg is not None:
        seg_mask = seg[:, :, None] == seg[:, None, :]  # [b, sq, sk]
        mask = seg_mask if mask is None else (mask & seg_mask)
    if mask is not None:
        s = jnp.where(mask[:, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.transpose(
        _weighted_v(p.astype(v.dtype), v), (0, 2, 1, 3)
    ).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    kv_block_size: int = 2048,
) -> jnp.ndarray:
    """Exact attention over sequence shards on the ``axis_name`` ring.

    Must be called inside a ``shard_map`` (or other collective context) where
    ``axis_name`` is bound; ``q, k, v`` are the local shards
    ``[b, s_local, h, d]`` of a global ``[b, s, h, d]``, all shards equal
    size.  Returns the local output shard.

    Each ring step is itself *blockwise* (the "blockwise transformers" half
    of Liu et al.): the arriving K/V shard is consumed in sub-blocks of at
    most ``kv_block_size`` through the same online-softmax recurrence (each
    sub-step ``jax.checkpoint``-ed, so the backward recomputes one
    sub-block at a time too), keeping transient AND residual score buffers
    at ``[b, h, s_local, sub]`` instead of ``[b, h, s_local, s_local]`` —
    large per-device shards (tens of k tokens) stay memory-feasible.  The
    sub count is the smallest divisor split of the shard with sub-blocks ≤
    ``kv_block_size`` (exact for any shard length).
    """
    b, sq, h, d = q.shape
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale
    sp = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % sp) for j in range(sp)]

    qpos = rank * sq + jnp.arange(sq)
    n_sub = 1
    if sq > kv_block_size:
        n_sub = -(-sq // kv_block_size)  # ceil
        while sq % n_sub != 0:  # nearest even split (worst case n_sub=sq)
            n_sub += 1
    sub = sq // n_sub

    def sub_update(o, l, m, kc, vc, kpos0):
        """Online-softmax accumulation of one K/V sub-block whose global
        positions start at ``kpos0``."""
        s = _scores(q, kc, sm_scale)  # [b, h, sq, sub] f32
        if causal:
            kpos = kpos0 + jnp.arange(kc.shape[1])
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask[None, None], s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)  # [b, h, sq]
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + _weighted_v(p.astype(vc.dtype), vc)
        return o_new, l_new, m_new

    def block_update(o, l, m, kc, vc, i):
        """One ring step: accumulate the K/V shard that originated on
        rank - i (equal shard sizes give its positions), sub-block by
        sub-block."""
        src = (rank - i) % sp
        if n_sub == 1:
            return sub_update(o, l, m, kc, vc, src * sq)

        def body(carry, jb):
            o, l, m = carry
            ks = lax.dynamic_slice_in_dim(kc, jb * sub, sub, 1)
            vs = lax.dynamic_slice_in_dim(vc, jb * sub, sub, 1)
            # checkpoint: without it the scan's backward would stack one
            # [b, h, sq, sub] softmax residual per sub-step — re-assembling
            # the full score matrix this sub-blocking exists to avoid.
            o, l, m = jax.checkpoint(sub_update)(
                o, l, m, ks, vs, src * sq + jb * sub
            )
            return (o, l, m), ()

        (o, l, m), _ = lax.scan(body, (o, l, m), jnp.arange(n_sub))
        return o, l, m

    def step(carry, i):
        o, l, m, kc, vc = carry
        o, l, m = block_update(o, l, m, kc, vc, i)
        k_next = lax.ppermute(kc, axis_name, perm)
        v_next = lax.ppermute(vc, axis_name, perm)
        return (o, l, m, k_next, v_next), ()

    # Step 0 processes the local (diagonal) block, so every causal query row
    # sees at least itself before any fully-masked block arrives; the running
    # max is finite from the first step on.
    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    m0 = jnp.full((b, h, sq), _NEG, jnp.float32)

    # Rotate only sp-1 times: the last block needs no onward hand-off, so its
    # ppermute pair never enters the program (it would sit on the critical
    # path of every attention call).
    (o, l, m, kc, vc), _ = lax.scan(
        jax.checkpoint(step), (o0, l0, m0, k, v), jnp.arange(sp - 1)
    )
    o, l, m = jax.checkpoint(block_update)(o, l, m, kc, vc, sp - 1)
    out = o / l[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def axis_bound(name: Optional[str]) -> bool:
    """True if ``name`` is a collective axis bound in the current trace.

    Layers use this so one ``apply`` serves both deployment shapes: inside a
    ``shard_map`` over ``name`` the sequence is sharded (ring path); outside
    — including init-time shape inference — the local array IS the whole
    sequence (dense path, same shapes).
    """
    if name is None:
        return False
    try:
        lax.psum(1, name)
    except NameError:
        return False
    return True


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: Optional[str] = None,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    kv_block_size: int = 2048,
    impl: str = "ring",
    window: Optional[int] = None,
    seg: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Dispatch: sequence-parallel attention when an sp axis is bound —
    ``impl='ring'`` (blockwise ring, O(s/sp) memory) or ``'ulysses'``
    (all_to_all head swap, full-sequence local compute; see
    :mod:`torchgpipe_tpu.parallel.ulysses`); on TPU the Pallas
    flash-attention kernel when shapes meet its tiling constraints
    (``TGPU_DISABLE_FLASH=1`` opts out); dense XLA attention otherwise.
    One call site serves every deployment shape.

    ``seg`` (``[b, s]`` segment ids — sequence packing, see
    :func:`full_attention`) takes the DENSE path unconditionally: the
    Pallas flash kernel has no segment-mask hook yet, so the packed
    training path falls back didactically to the masked XLA einsum
    (documented in docs/tuning.md; the dense mask is the oracle the
    kernel will be tested against when it grows the hook), and the
    sequence-parallel impls do not compose with packing (shards would
    need cross-shard segment routing)."""
    from torchgpipe_tpu.ops.flash_attention import _validate_window

    if impl not in ("ring", "ulysses"):
        raise ValueError("attention impl must be 'ring' or 'ulysses'")
    _validate_window(causal, window)
    if seg is not None:
        if axis_bound(axis_name):
            raise ValueError(
                "segment-packed attention does not compose with a bound "
                "sequence-parallel axis (ring/ulysses shards would need "
                "cross-shard segment routing); drop sp_axis for packed "
                "training"
            )
        return full_attention(
            q, k, v, causal=causal, sm_scale=sm_scale, window=window,
            seg=seg,
        )
    if not axis_bound(axis_name):
        import os

        from torchgpipe_tpu.ops import flash_attention as _fa

        dense = lambda q, k, v: full_attention(  # noqa: E731
            q, k, v, causal=causal, sm_scale=sm_scale, window=window
        )
        # Exact-tile heads (d % 128 == 0) take the kernel at any supported
        # length; padded heads (d < 128, e.g. the Llama-1B-class head_dim
        # 64) only where flash is measured to win over dense XLA
        # (seq >= PADDED_HEAD_MIN_SEQ) — this is what puts the kernel in
        # the TRAINING path at seq >= 2048 for the 1B preset.
        if (
            not os.environ.get("TGPU_DISABLE_FLASH")
            and _fa.supports(q.shape, k.shape)
            and (
                q.shape[3] % 128 == 0
                or q.shape[1] >= _fa.PADDED_HEAD_MIN_SEQ
            )
        ):
            # Resolved at RUN time by platform_index: TPU executes the
            # kernel branch, everything else the dense branch.  The
            # kernel is traced with interpret=True on non-TPU hosts —
            # this jax lowers EVERY platform_dependent branch for the
            # current platform, and Mosaic has no CPU lowering, so the
            # compiled-kernel spelling would break CPU lowering outright
            # (the interpret spelling lowers everywhere and is dead code
            # at runtime off-TPU).  Net effect: the training jaxpr
            # carries the real pallas_call on every host — statically
            # checkable on CPU — while only TPU lowering emits Mosaic.
            # Known hole (pre-existing on this jax, either spelling): a
            # CPU-TARGETED lowering on a TPU-backend host (CPU oracle
            # under jax.default_device(cpu)) still lowers the Mosaic
            # branch for CPU and fails — run such oracles under
            # TGPU_DISABLE_FLASH=1.
            interpret = jax.default_backend() != "tpu"
            return lax.platform_dependent(
                q, k, v,
                tpu=lambda q, k, v: _fa.flash_attention(
                    q, k, v, causal=causal, sm_scale=sm_scale, window=window,
                    interpret=interpret,
                ),
                default=dense,
            )
        return dense(q, k, v)
    if impl == "ulysses":
        from torchgpipe_tpu.parallel.ulysses import ulysses_attention

        return ulysses_attention(
            q, k, v, axis_name, causal=causal, sm_scale=sm_scale,
            window=window,
        )
    if window is not None:
        raise ValueError(
            "sliding-window attention does not compose with the ring sp "
            "path yet (the ring would need per-step band skipping); use "
            "sp_impl='ulysses' — its local full-sequence attention "
            "windows exactly — or drop the sp axis"
        )
    return ring_attention(
        q, k, v, axis_name, causal=causal, sm_scale=sm_scale,
        kv_block_size=kv_block_size,
    )
