"""TPU-friendly neural-net building blocks used by the model zoo."""

from torchgpipe_tpu.ops.nn import (  # noqa: F401
    avg_pool2d,
    batch_norm,
    conv2d,
    dense,
    dropout,
    flatten,
    gelu,
    global_avg_pool,
    layer_norm,
    max_pool2d,
    relu,
)
