"""TPU-friendly neural-net building blocks used by the model zoo."""

from torchgpipe_tpu.ops.nn import (  # noqa: F401
    avg_pool2d,
    batch_norm,
    conv2d,
    dense,
    dropout,
    dropout2d,
    flatten,
    gelu,
    global_avg_pool,
    instance_norm,
    layer_norm,
    leaky_relu,
    max_pool2d,
    relu,
    upsample2d,
)
