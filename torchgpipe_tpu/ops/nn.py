"""Minimal neural-net layer library over :class:`torchgpipe_tpu.layers.Layer`.

The reference leans on ``torch.nn`` for actual math; this framework supplies
its own thin layer set so models are plain JAX and lower cleanly onto the MXU:

* images are NHWC (TPU-preferred layout; the reference's NCHW is a CUDA habit),
* convolutions use ``lax.conv_general_dilated`` with NHWC/HWIO dimension
  numbers, which XLA tiles onto the systolic array,
* all layers are pure functions of explicit params/state pytrees.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from torchgpipe_tpu.layers import Layer, stateless


def _kaiming(
    rng: jax.Array,
    shape: Tuple[int, ...],
    fan_in: int,
    dtype: Any = jnp.float32,
) -> jnp.ndarray:
    std = (2.0 / fan_in) ** 0.5
    return std * jax.random.normal(rng, shape, dtype)


def dense(features: int, *, use_bias: bool = True, name: str = "dense") -> Layer:
    """Fully-connected layer ``y = x @ W + b`` over the trailing dim."""

    def init(rng, in_spec):
        in_features = jax.tree_util.tree_leaves(in_spec)[0].shape[-1]
        wkey, _ = jax.random.split(rng)
        params = {"w": _kaiming(wkey, (in_features, features), in_features)}
        if use_bias:
            params["b"] = jnp.zeros((features,))
        return params, ()

    def apply(params, state, x, *, rng=None, train=True):
        del rng, train
        y = x @ params["w"]
        if use_bias:
            y = y + params["b"]
        return y, state

    return Layer(name=name, init=init, apply=apply)


def conv2d(
    features: int,
    kernel_size: Tuple[int, int] = (3, 3),
    *,
    strides: Tuple[int, int] = (1, 1),
    padding: Any = 'SAME',
    use_bias: bool = False,
    feature_group_count: int = 1,
    name: str = 'conv',
) -> Layer:
    """2-D convolution, NHWC activations, HWIO kernel."""

    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    if isinstance(strides, int):
        strides = (strides, strides)

    def init(rng, in_spec):
        in_ch = jax.tree_util.tree_leaves(in_spec)[0].shape[-1]
        kh, kw = kernel_size
        fan_in = kh * kw * in_ch // feature_group_count
        w = _kaiming(
            rng, (kh, kw, in_ch // feature_group_count, features), fan_in
        )
        params = {"w": w}
        if use_bias:
            params["b"] = jnp.zeros((features,))
        return params, ()

    def apply(params, state, x, *, rng=None, train=True):
        del rng, train
        y = lax.conv_general_dilated(
            x,
            params["w"],
            window_strides=strides,
            padding=padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=feature_group_count,
        )
        if use_bias:
            y = y + params["b"]
        return y, state

    return Layer(name=name, init=init, apply=apply)


def batch_norm(
    *, momentum: float = 0.9, eps: float = 1e-5, name: str = "bn"
) -> Layer:
    """Standard BatchNorm over all but the channel (last) axis.

    Per-micro-batch statistics; see :mod:`torchgpipe_tpu.batchnorm` for the
    deferred (mini-batch-faithful) variant the pipeline offers
    (reference: torchgpipe/batchnorm.py:17-121).
    """

    def init(rng, in_spec):
        del rng
        ch = jax.tree_util.tree_leaves(in_spec)[0].shape[-1]
        params = {"scale": jnp.ones((ch,)), "bias": jnp.zeros((ch,))}
        state = {"mean": jnp.zeros((ch,)), "var": jnp.ones((ch,))}
        return params, state

    def apply(params, state, x, *, rng=None, train=True):
        del rng
        axes = tuple(range(x.ndim - 1))
        if train:
            mean = jnp.mean(x, axes)
            var = jnp.var(x, axes)
            new_state = {
                "mean": momentum * state["mean"] + (1 - momentum) * mean,
                "var": momentum * state["var"] + (1 - momentum) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        y = (x - mean) * lax.rsqrt(var + eps)
        y = y * params["scale"] + params["bias"]
        return y, new_state

    return Layer(
        name=name,
        init=init,
        apply=apply,
        meta={"kind": "batch_norm", "momentum": momentum, "eps": eps},
    )


def layer_norm(*, eps: float = 1e-6, name: str = "ln") -> Layer:
    """LayerNorm over the trailing dim."""

    def init(rng, in_spec):
        del rng
        ch = jax.tree_util.tree_leaves(in_spec)[0].shape[-1]
        return {"scale": jnp.ones((ch,)), "bias": jnp.zeros((ch,))}, ()

    def apply(params, state, x, *, rng=None, train=True):
        del rng, train
        mean = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        y = (x - mean) * lax.rsqrt(var + eps)
        return y * params["scale"] + params["bias"], state

    return Layer(
        name=name, init=init, apply=apply, meta={"kind": "layer_norm", "eps": eps}
    )


def dropout(rate: float, *, name: str = "dropout") -> Layer:
    """Inverted dropout; a counter-based key per micro-batch makes recompute
    deterministic (replaces reference RNG capture, checkpoint.py:191-231)."""

    def init(rng, in_spec):
        del rng, in_spec
        return (), ()

    def apply(params, state, x, *, rng=None, train=True):
        del params
        if not train or rate == 0.0:
            return x, state
        if rng is None:
            raise ValueError("dropout needs an rng key in train mode")
        keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
        return jnp.where(keep, x / (1.0 - rate), 0.0), state

    return Layer(name=name, init=init, apply=apply)


def relu(name: str = "relu") -> Layer:
    return stateless(name, jax.nn.relu)


def gelu(name: str = "gelu") -> Layer:
    return stateless(name, jax.nn.gelu)


def _pool(
    x: jnp.ndarray,
    window: Tuple[int, int],
    strides: Tuple[int, int],
    padding: Any,
    reducer: Callable,
    init_val: float,
) -> jnp.ndarray:
    dims = (1, window[0], window[1], 1)
    strs = (1, strides[0], strides[1], 1)
    if not isinstance(padding, str):
        # Spatial ((lo, hi), (lo, hi)) pairs — expand to all 4 NHWC dims.
        padding = ((0, 0), tuple(padding[0]), tuple(padding[1]), (0, 0))
    return lax.reduce_window(x, init_val, reducer, dims, strs, padding)


def max_pool2d(
    window: Tuple[int, int] = (2, 2),
    strides: Optional[Tuple[int, int]] = None,
    *,
    padding: str = "VALID",
    name: str = "maxpool",
) -> Layer:
    if isinstance(window, int):
        window = (window, window)
    strides = strides or window

    def fn(x):
        return _pool(x, window, strides, padding, lax.max, -jnp.inf)

    return stateless(name, fn)


def avg_pool2d(
    window: Tuple[int, int] = (2, 2),
    strides: Optional[Tuple[int, int]] = None,
    *,
    padding: str = "VALID",
    count_include_pad: bool = True,
    name: str = "avgpool",
) -> Layer:
    if isinstance(window, int):
        window = (window, window)
    strides = strides or window

    def fn(x):
        summed = _pool(x, window, strides, padding, lax.add, 0.0)
        if count_include_pad or padding == "VALID":
            return summed / (window[0] * window[1])
        ones = jnp.ones_like(x)
        counts = _pool(ones, window, strides, padding, lax.add, 0.0)
        return summed / counts

    return stateless(name, fn)


def instance_norm(*, eps: float = 1e-5, name: str = "in") -> Layer:
    """InstanceNorm over spatial dims, per sample per channel, no affine
    params and no running stats (the torch ``InstanceNorm2d`` defaults the
    reference's U-Net uses, benchmarks/models/unet/__init__.py:46)."""

    def fn(x):
        axes = tuple(range(1, x.ndim - 1))
        mean = jnp.mean(x, axes, keepdims=True)
        var = jnp.var(x, axes, keepdims=True)
        return (x - mean) * lax.rsqrt(var + eps)

    layer = stateless(name, fn)
    return dataclasses.replace(layer, meta={"kind": "instance_norm", "eps": eps})


def leaky_relu(negative_slope: float = 0.01, *, name: str = "leaky_relu") -> Layer:
    return stateless(name, lambda x: jax.nn.leaky_relu(x, negative_slope))


def dropout2d(rate: float, *, name: str = "dropout2d") -> Layer:
    """Spatial (channel-wise) dropout: zero whole feature maps, NHWC."""

    def init(rng, in_spec):
        del rng, in_spec
        return (), ()

    def apply(params, state, x, *, rng=None, train=True):
        del params
        if not train or rate == 0.0:
            return x, state
        if rng is None:
            raise ValueError("dropout2d needs an rng key in train mode")
        mask_shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
        keep = jax.random.bernoulli(rng, 1.0 - rate, mask_shape)
        return jnp.where(keep, x / (1.0 - rate), 0.0), state

    return Layer(name=name, init=init, apply=apply)


def upsample2d(scale: int = 2, *, name: str = "upsample") -> Layer:
    """Nearest-neighbour spatial upsampling (NHWC)."""

    def fn(x):
        x = jnp.repeat(x, scale, axis=1)
        return jnp.repeat(x, scale, axis=2)

    return stateless(name, fn)


def global_avg_pool(name: str = "gap") -> Layer:
    return stateless(name, lambda x: jnp.mean(x, axis=(1, 2)))


def flatten(name: str = "flatten") -> Layer:
    return stateless(name, lambda x: x.reshape(x.shape[0], -1))
