"""Memory-bounded loss kernels.

:func:`chunked_softmax_xent` is the big-vocabulary cross-entropy: the
``[T, V]`` logit matrix of a language-model head is the largest single
tensor in small-pipeline training (e.g. a 128k vocabulary at 4k tokens is
2 GiB in f32 — the recorded OOM blocker for the 1B-preset runs on a 16 GB
chip, BENCH_NOTES.md).  Instead of materializing it, the head matmul and
the softmax-cross-entropy are fused into one ``lax.scan`` over vocabulary
chunks with online log-sum-exp state — peak extra memory is one
``[T, chunk]`` tile, independent of V.  The backward pass recomputes each
chunk's logits and emits the weight-gradient chunkwise (a second scan),
so no ``[T, V]`` tensor exists in either direction.

New TPU-native capability (the reference is CNN-oriented and has no loss
kernels); the online-softmax structure mirrors the flash-attention
forward (ops/flash_attention.py) applied to the classifier axis.
"""

from __future__ import annotations

from typing import Tuple

from functools import partial
import jax
import jax.numpy as jnp
from jax import lax

_NEG = jnp.float32(-1e30)


def _chunks(w: jnp.ndarray, chunk: int) -> Tuple[int, int]:
    """``[d, V] -> ([n, d, C], offsets [n])`` with zero padding on V."""
    d, V = w.shape
    n = -(-V // chunk)
    pad = n * chunk - V
    wp = jnp.pad(w, ((0, 0), (0, pad)))
    return (
        jnp.transpose(wp.reshape(d, n, chunk), (1, 0, 2)),
        jnp.arange(n, dtype=jnp.int32) * chunk,
    )


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def chunked_softmax_xent(
    h: jnp.ndarray, w: jnp.ndarray, labels: jnp.ndarray, chunk: int = 8192
) -> jnp.ndarray:
    """Per-token cross-entropy ``-log softmax(h @ w)[label]`` without ever
    materializing the ``[T, V]`` logits.

    ``h``: ``[T, d]`` hidden states (any float dtype; logits accumulate in
    f32), ``w``: ``[d, V]`` head weights, ``labels``: ``[T]`` int.  Returns
    ``[T]`` f32 losses (reduce yourself — ``jnp.mean`` for the usual mean
    objective).  ``chunk`` bounds the transient tile: peak extra memory is
    ``T * chunk`` f32 instead of ``T * V``.

    Labels MUST lie in ``[0, V)``.  An out-of-range label (including a
    negative "ignore-index" convention) matches no vocabulary chunk, so
    its target term silently stays 0 and the returned value degrades to
    ``logsumexp`` — a plausible-looking positive number, not an error,
    where a dense ``take_along_axis`` oracle would have gathered garbage
    loudly.  There is no ignore-index semantics here: mask such tokens'
    losses to 0 yourself after the call (and scale your mean by the kept
    count).  Use :func:`assert_labels_in_range` under
    ``jax.experimental.checkify`` to make violations loud in debug runs.
    """
    loss, _, _ = _xent_fwd_scan(h, w, labels, chunk)
    return loss


def _xent_fwd_scan(
    h: jnp.ndarray,
    w: jnp.ndarray,
    labels: jnp.ndarray,
    chunk: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    V = w.shape[1]
    wc, offs = _chunks(w, chunk)

    def body(carry, xs):
        m, s, tl = carry
        w_c, off = xs
        logits = (h @ w_c).astype(jnp.float32)  # [T, C]
        valid = off + jnp.arange(chunk) < V
        logits = jnp.where(valid[None, :], logits, _NEG)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1
        )
        in_r = (labels >= off) & (labels < off + chunk)
        idx = jnp.clip(labels - off, 0, chunk - 1)
        picked = jnp.take_along_axis(logits, idx[:, None], axis=-1)[:, 0]
        tl = tl + jnp.where(in_r, picked, 0.0)
        return (m_new, s, tl), None

    T = h.shape[0]
    init = (
        jnp.full((T,), _NEG, jnp.float32),
        jnp.zeros((T,), jnp.float32),
        jnp.zeros((T,), jnp.float32),
    )
    (m, s, tl), _ = lax.scan(body, init, (wc, offs))
    lse = jnp.log(s) + m
    return lse - tl, m, s


def _xent_vjp_fwd(
    h: jnp.ndarray,
    w: jnp.ndarray,
    labels: jnp.ndarray,
    chunk: int,
) -> Tuple[jnp.ndarray, Tuple]:
    loss, m, s = _xent_fwd_scan(h, w, labels, chunk)
    return loss, (h, w, labels, m, s)


def _xent_vjp_bwd(
    chunk: int,
    res: Tuple,
    g: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, None]:
    """``g``: ``[T]`` cotangent of the per-token losses.

    ``dlogits = softmax - onehot(label)`` per token; both gradients are
    assembled chunkwise from recomputed logits:
    ``dh = Σ_c (g ⊙ p_c) @ w_cᵀ`` and ``dw_c = hᵀ @ (g ⊙ p_c)``.
    """
    h, w, labels, m, s = res
    V = w.shape[1]
    wc, offs = _chunks(w, chunk)
    lse = jnp.log(s) + m
    # Loop-invariant casts hoisted out of the scan body; dh accumulates in
    # f32 across the V/chunk iterations (a low-precision carry would
    # compound one rounding per chunk — the dense oracle rounds once) and
    # is cast back to h.dtype after the scan.
    h32T = h.astype(jnp.float32).T  # [d, T]

    def body(dh, xs):
        w_c, off = xs
        logits = (h @ w_c).astype(jnp.float32)
        valid = off + jnp.arange(chunk) < V
        logits = jnp.where(valid[None, :], logits, _NEG)
        p = jnp.exp(logits - lse[:, None])  # softmax chunk [T, C]
        in_r = (labels >= off) & (labels < off + chunk)
        idx = jnp.clip(labels - off, 0, chunk - 1)
        onehot = (
            jax.nn.one_hot(idx, chunk, dtype=p.dtype)
            * in_r[:, None].astype(p.dtype)
        )
        dl = (p - onehot) * g[:, None]  # [T, C] f32
        dh = dh + dl @ w_c.astype(jnp.float32).T
        dw_c = (h32T @ dl).astype(w.dtype)  # [d, C]
        return dh, dw_c

    dh0 = jnp.zeros(h.shape, jnp.float32)
    dh, dw_chunks = lax.scan(body, dh0, (wc, offs))
    dh = dh.astype(h.dtype)
    # [n, d, C] -> [d, n*C] -> trim padding -> [d, V]
    dw = jnp.transpose(dw_chunks, (1, 0, 2)).reshape(w.shape[0], -1)[:, :V]
    return dh, dw, None


chunked_softmax_xent.defvjp(_xent_vjp_fwd, _xent_vjp_bwd)


def assert_labels_in_range(labels: jnp.ndarray, vocab: int) -> None:
    """Checkify-able guard for :func:`chunked_softmax_xent`'s label
    contract (labels in ``[0, V)`` — out-of-range labels silently lose
    their target term).  Call it right before the loss inside a function
    wrapped with ``jax.experimental.checkify.checkify``; outside checkify
    the ``debug=True`` check is dropped at staging (verified under plain
    ``jit``), so production steps pay nothing.
    """
    from jax.experimental import checkify

    checkify.check(
        jnp.all((labels >= 0) & (labels < vocab)),
        "chunked_softmax_xent: labels must lie in [0, vocab); out-of-range "
        "labels would silently degrade the loss to logsumexp",
        debug=True,
    )
