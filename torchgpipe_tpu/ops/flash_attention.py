"""Flash attention as Pallas TPU kernels (forward + backward).

The framework's hot op: fused online-softmax attention that never
materializes the ``[s, s]`` score matrix in HBM — scores live in VMEM one
``[block_q, block_k]`` tile at a time, with f32 accumulation on the MXU.
Backward follows the standard flash decomposition (Dao, FlashAttention-2;
public algorithm, implemented here from the math against
/opt/skills/guides/pallas_guide.md):

* forward saves only ``O`` and the per-row logsumexp ``L``,
* ``dQ`` kernel re-streams K/V tiles; ``dK/dV`` kernel re-streams Q tiles,
* ``D = rowsum(dO * O)`` is precomputed outside the kernels (cheap
  elementwise reduce that XLA fuses).

Supports causal masking and grouped-query attention (K/V at ``g`` heads,
queries at ``h = g*r``); the kernels are gridded over ``(batch*heads,
sequence blocks)`` so each program works on MXU-aligned ``[block, d]``
tiles.  ``torchgpipe_tpu.parallel.attention`` dispatches here on TPU when
shapes meet the tiling constraints (``d`` and ``s`` multiples of 128),
falling back to the XLA path otherwise; ``interpret=True`` runs the same
kernels on CPU for the test oracle.

The reference has no kernel of any kind — its attention story is absent
entirely (SURVEY.md §2.2); this module is TPU-native new capability.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30

# K/V rows resident in VMEM per program beyond roughly this many bytes tip
# the kernels into the streaming (third-grid-dimension) variants, which keep
# only one [block, d] tile of K/V in VMEM at a time.
_STREAM_BYTES = 4 * 1024 * 1024


def _validate_window(causal: bool, window: Optional[int]) -> None:
    """Shared entry-point validation for sliding-window attention."""
    if window is None:
        return
    if not causal:
        raise ValueError(
            "window (sliding-window attention) requires causal=True"
        )
    if window < 1:
        raise ValueError("window must be >= 1")


def _kv_index(i: jax.Array, h: int, g: int) -> jax.Array:
    """Row in the [b*g, s, d] K/V array for query row ``i`` of [b*h, s, d]."""
    r = h // g
    return (i // h) * g + (i % h) // r


# --------------------------------------------------------------------- #
# forward                                                               #
# --------------------------------------------------------------------- #


def _fwd_kernel(
    q_ref: Any,
    k_ref: Any,
    v_ref: Any,
    o_ref: Any,
    lse_ref: Any,
    *,
    causal: bool,
    sm_scale: float,
    block_q: int,
    block_k: int,
    seq_k: int,
    window: Optional[int],
) -> None:
    j = pl.program_id(1)
    qb = q_ref[0].astype(jnp.float32) * sm_scale  # [Bq, d]
    nk = seq_k // block_k
    jk0 = 0
    if causal:
        # Only KV blocks overlapping the causal triangle (banded by the
        # sliding window when set) of this Q block.
        nk = lax.min(nk, lax.div((j + 1) * block_q + block_k - 1, block_k))
        if window is not None:
            jk0 = _first_valid_kv(j, block_q, block_k, window)

    def body(jb, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(jb * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(jb * block_k, block_k), :].astype(jnp.float32)
        s = lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [Bq, Bk]
        if causal:
            s = _mask_causal(s, j, jb, block_q, block_k, window)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * corr + lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    m, l, acc = lax.fori_loop(jk0, nk, body, (m0, l0, acc0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)  # [Bq, 1]


def _flash_fwd_call(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    h: int,
    g: int,
    causal: bool,
    sm_scale: float,
    block_q: int,
    block_k: int,
    interpret: bool,
    window: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    bh, s, d = q.shape
    grid = (bh, s // block_q)
    kv_spec = pl.BlockSpec(
        (1, k.shape[1], d), lambda i, j: (_kv_index(i, h, g), 0, 0)
    )
    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, seq_k=k.shape[1],
            window=window,
        ),
        out_shape=(
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            kv_spec,
            kv_spec,
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0)),
        ),
        interpret=interpret,
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------- #
# streaming variants: K/V (or Q) tiles stream from HBM on a third grid  #
# dimension, with the online-softmax state carried in VMEM scratch —    #
# per-program VMEM is O(block·d) regardless of sequence length, which   #
# is what very long single-chip sequences (≳32k) need.  The TPU grid    #
# iterates its trailing dimension sequentially, so scratch accumulates  #
# correctly across the K/V steps of one (row, q-block) cell.            #
# --------------------------------------------------------------------- #


def _causal_overlap(
    jq: jax.Array,
    jk: jax.Array,
    block_q: int,
    block_k: int,
    window: Optional[int] = None,
) -> jax.Array:
    """Whether q block jq has any unmasked position against k block jk
    under causal masking, optionally banded by a sliding ``window``
    (attend iff ``0 <= qpos - kpos < window``)."""
    ok = (jq + 1) * block_q - 1 >= jk * block_k
    if window is not None:
        # Block-level band check: some (qpos, kpos) pair in the blocks has
        # qpos - kpos < window, i.e. the SMALLEST difference in the pair of
        # blocks (first q row vs last k col) is below the window.
        ok = ok & (jq * block_q - ((jk + 1) * block_k - 1) < window)
    return ok


def _last_valid_kv(jq: jax.Array, block_q: int, block_k: int) -> jax.Array:
    """Largest K/V block index with any unmasked position for q block
    ``jq`` under causal masking (== the diagonal block)."""
    return ((jq + 1) * block_q - 1) // block_k


def _first_valid_kv(
    jq: jax.Array,
    block_q: int,
    block_k: int,
    window: Optional[int] = None,
) -> jax.Array:
    """Smallest K/V block index inside the sliding window for q block
    ``jq`` (0 without a window)."""
    if window is None:
        return 0
    lo = jq * block_q - (window - 1)  # kpos of the oldest visible key
    return jnp.maximum(lo, 0) // block_k


def _first_valid_q(jk: jax.Array, block_q: int, block_k: int) -> jax.Array:
    """Smallest q block index with any unmasked position against K/V
    block ``jk`` under causal masking."""
    return (jk * block_k) // block_q


def _last_valid_q(
    jk: jax.Array,
    block_q: int,
    block_k: int,
    nq: int,
    window: Optional[int] = None,
) -> jax.Array:
    """Largest q block index inside the sliding window for K/V block
    ``jk`` (``nq - 1`` without a window)."""
    if window is None:
        return nq - 1
    hi = (jk + 1) * block_k - 1 + window - 1  # newest query seeing block jk
    return jnp.minimum(hi // block_q, nq - 1)


# Causal block-skipping for the streaming grids: the TPU grid is
# rectangular, but clamping the BLOCK INDEX MAP to the last/first valid
# block makes every fully-masked cell re-request the tile already in
# VMEM — Pallas's pipelining skips the HBM copy when the block index is
# unchanged between iterations, and ``pl.when`` skips the compute.  Net:
# masked cells cost one grid bump, no bandwidth, no FLOPs (the reason
# streaming used to lose to dense at moderate causal lengths —
# BENCH_NOTES round-2 table, 87.1 vs 64.8 ms @4k).


def _clamped_kv_block(
    j: jax.Array,
    jk: jax.Array,
    block_q: int,
    block_k: int,
    causal: bool,
    window: Optional[int] = None,
) -> jax.Array:
    """K/V block to FETCH at streaming grid cell (q block j, step jk):
    clipped into the valid causal/window band so masked cells re-request
    a resident tile."""
    if not causal:
        return jk
    return jnp.clip(
        jk,
        _first_valid_kv(j, block_q, block_k, window),
        _last_valid_kv(j, block_q, block_k),
    )


def _clamped_q_block(
    jk: jax.Array,
    jq: jax.Array,
    block_q: int,
    block_k: int,
    causal: bool,
    nq: int,
    window: Optional[int] = None,
) -> jax.Array:
    """Q block to FETCH at streaming dK/dV grid cell (kv block jk, step
    jq), clipped into the valid causal/window band."""
    if not causal:
        return jq
    return jnp.clip(
        jq,
        _first_valid_q(jk, block_q, block_k),
        _last_valid_q(jk, block_q, block_k, nq, window),
    )


def _mask_causal(
    s: jnp.ndarray,
    jq: jax.Array,
    jk: jax.Array,
    block_q: int,
    block_k: int,
    window: Optional[int] = None,
) -> jnp.ndarray:
    qpos = jq * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = jk * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    m = qpos >= kpos
    if window is not None:
        m = m & (qpos - kpos < window)
    return jnp.where(m, s, _NEG)


def _fwd_stream_kernel(
    q_ref: Any,
    k_ref: Any,
    v_ref: Any,
    o_ref: Any,
    lse_ref: Any,
    m_sc: Any,
    l_sc: Any,
    acc_sc: Any,
    *,
    causal: bool,
    sm_scale: float,
    block_q: int,
    block_k: int,
    nk: int,
    window: Optional[int],
) -> None:
    j = pl.program_id(1)
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    run = (
        _causal_overlap(j, jk, block_q, block_k, window)
        if causal else jk >= 0
    )

    @pl.when(run)
    def _body():
        qb = q_ref[0].astype(jnp.float32) * sm_scale
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            s = _mask_causal(s, j, jk, block_q, block_k, window)
        m_prev = m_sc[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_sc[...] = l_sc[...] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_sc[...] = acc_sc[...] * corr + lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_sc[...] = m_new

    @pl.when(jk == nk - 1)
    def _finish():
        o_ref[0] = (acc_sc[...] / l_sc[...]).astype(o_ref.dtype)
        lse_ref[0] = m_sc[...] + jnp.log(l_sc[...])


def _flash_fwd_call_stream(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    h: int,
    g: int,
    causal: bool,
    sm_scale: float,
    block_q: int,
    block_k: int,
    interpret: bool,
    window: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    bh, s, d = q.shape
    sk = k.shape[1]
    nk = sk // block_k
    grid = (bh, s // block_q, nk)
    kv_im = lambda i, j, jk: (  # noqa: E731
        _kv_index(i, h, g),
        _clamped_kv_block(j, jk, block_q, block_k, causal, window),
        0,
    )
    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_stream_kernel, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, nk=nk, window=window,
        ),
        out_shape=(
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, jk: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), kv_im),
            pl.BlockSpec((1, block_k, d), kv_im),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda i, j, jk: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j, jk: (i, j, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _dq_stream_kernel(
    q_ref: Any,
    k_ref: Any,
    v_ref: Any,
    do_ref: Any,
    lse_ref: Any,
    delta_ref: Any,
    dq_ref: Any,
    dq_sc: Any,
    *,
    causal: bool,
    sm_scale: float,
    block_q: int,
    block_k: int,
    nk: int,
    window: Optional[int],
) -> None:
    j = pl.program_id(1)
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    run = (
        _causal_overlap(j, jk, block_q, block_k, window)
        if causal else jk >= 0
    )

    @pl.when(run)
    def _body():
        qb = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        dob = do_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if causal:
            s = _mask_causal(s, j, jk, block_q, block_k, window)
        p = jnp.exp(s - lse_ref[0])
        dp = lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0])
        dq_sc[...] = dq_sc[...] + lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jk == nk - 1)
    def _finish():
        dq_ref[0] = (dq_sc[...] * sm_scale).astype(dq_ref.dtype)


def _dkv_stream_kernel(
    q_ref: Any,
    k_ref: Any,
    v_ref: Any,
    do_ref: Any,
    lse_ref: Any,
    delta_ref: Any,
    dk_ref: Any,
    dv_ref: Any,
    dk_sc: Any,
    dv_sc: Any,
    *,
    causal: bool,
    sm_scale: float,
    block_q: int,
    block_k: int,
    nq: int,
    window: Optional[int],
) -> None:
    jk = pl.program_id(1)
    jq = pl.program_id(2)

    @pl.when(jq == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    run = (
        _causal_overlap(jq, jk, block_q, block_k, window)
        if causal else jq >= 0
    )

    @pl.when(run)
    def _body():
        qb = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        dob = do_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if causal:
            s = _mask_causal(s, jq, jk, block_q, block_k, window)
        p = jnp.exp(s - lse_ref[0])
        dv_sc[...] = dv_sc[...] + lax.dot_general(
            p, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0])
        dk_sc[...] = dk_sc[...] + lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jq == nq - 1)
    def _finish():
        dk_ref[0] = (dk_sc[...] * sm_scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


# --------------------------------------------------------------------- #
# backward                                                              #
# --------------------------------------------------------------------- #


def _dq_kernel(
    q_ref: Any,
    k_ref: Any,
    v_ref: Any,
    do_ref: Any,
    lse_ref: Any,
    delta_ref: Any,
    dq_ref: Any,
    *,
    causal: bool,
    sm_scale: float,
    block_q: int,
    block_k: int,
    seq_k: int,
    window: Optional[int],
) -> None:
    j = pl.program_id(1)
    qb = q_ref[0].astype(jnp.float32)
    dob = do_ref[0].astype(jnp.float32)
    lse_b = lse_ref[0]      # [Bq, 1]
    delta_b = delta_ref[0]  # [Bq, 1]
    nk = seq_k // block_k
    jk0 = 0
    if causal:
        nk = lax.min(nk, lax.div((j + 1) * block_q + block_k - 1, block_k))
        if window is not None:
            jk0 = _first_valid_kv(j, block_q, block_k, window)

    def body(jb, dq):
        kb = k_ref[0, pl.ds(jb * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(jb * block_k, block_k), :].astype(jnp.float32)
        s = lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if causal:
            s = _mask_causal(s, j, jb, block_q, block_k, window)
        p = jnp.exp(s - lse_b)  # [Bq, Bk]
        dp = lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_b)
        return dq + lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = lax.fori_loop(
        jk0, nk, body, jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    )
    dq_ref[0] = (dq * sm_scale).astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref: Any,
    k_ref: Any,
    v_ref: Any,
    do_ref: Any,
    lse_ref: Any,
    delta_ref: Any,
    dk_ref: Any,
    dv_ref: Any,
    *,
    causal: bool,
    sm_scale: float,
    block_q: int,
    block_k: int,
    seq_q: int,
    window: Optional[int],
) -> None:
    jk = pl.program_id(1)
    kb = k_ref[0].astype(jnp.float32)  # [Bk, d]
    vb = v_ref[0].astype(jnp.float32)
    nq = seq_q // block_q
    jq0 = lax.div(jk * block_k, block_q) if causal else 0
    jq_hi = (
        _last_valid_q(jk, block_q, block_k, nq, window) + 1
        if causal else nq
    )

    def body(jq, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(jq * block_q, block_q), :].astype(jnp.float32)
        dob = do_ref[0, pl.ds(jq * block_q, block_q), :].astype(jnp.float32)
        lse_b = lse_ref[0, pl.ds(jq * block_q, block_q), :]      # [Bq, 1]
        delta_b = delta_ref[0, pl.ds(jq * block_q, block_q), :]  # [Bq, 1]
        s = lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if causal:
            s = _mask_causal(s, jq, jk, block_q, block_k, window)
        p = jnp.exp(s - lse_b)  # [Bq, Bk]
        dv_new = dv + lax.dot_general(
            p, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_b)
        dk_new = dk + lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk_new, dv_new

    d = k_ref.shape[-1]
    dk, dv = lax.fori_loop(
        jq0, jq_hi, body,
        (jnp.zeros((block_k, d), jnp.float32),
         jnp.zeros((block_k, d), jnp.float32)),
    )
    dk_ref[0] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# --------------------------------------------------------------------- #
# custom_vjp wiring                                                     #
# --------------------------------------------------------------------- #


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10)
)
def _flash(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    h: int,
    g: int,
    causal: bool,
    sm_scale: float,
    blocks: Optional[Tuple[int, int]],
    interpret: bool,
    streaming: bool,
    window: Optional[int],
) -> jnp.ndarray:
    fwd = _flash_fwd_call_stream if streaming else _flash_fwd_call
    o, _ = fwd(
        q, k, v, h, g, causal, sm_scale, blocks[0], blocks[1], interpret,
        window,
    )
    return o


def _flash_vjp_fwd(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    h: int,
    g: int,
    causal: bool,
    sm_scale: float,
    blocks: Optional[Tuple[int, int]],
    interpret: bool,
    streaming: bool,
    window: Optional[int],
) -> Tuple:
    from jax.ad_checkpoint import checkpoint_name

    fwd = _flash_fwd_call_stream if streaming else _flash_fwd_call
    o, lse = fwd(
        q, k, v, h, g, causal, sm_scale, blocks[0], blocks[1], interpret,
        window,
    )
    # Checkpoint-named so remat policies compose with the kernel: a policy
    # saving "flash_out"/"flash_stats" keeps (or host-offloads) the vjp
    # residuals and the backward never replays the forward kernel; a
    # policy dropping them recomputes the kernel once in the backward
    # (checkpoint.NAMED_SAVE_POINTS; docs/tuning.md).
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_stats")
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(
    h: int,
    g: int,
    causal: bool,
    sm_scale: float,
    blocks: Optional[Tuple[int, int]],
    interpret: bool,
    streaming: bool,
    window: Optional[int],
    res: Tuple,
    do: jnp.ndarray,
) -> Tuple:
    if streaming:
        return _flash_bwd_stream(
            h, g, causal, sm_scale, blocks, interpret, res, do, window
        )
    return _flash_bwd_resident(
        h, g, causal, sm_scale, blocks, interpret, res, do, window
    )


def _flash_bwd_stream(
    h: int,
    g: int,
    causal: bool,
    sm_scale: float,
    blocks: Optional[Tuple[int, int]],
    interpret: bool,
    res: Tuple,
    do: jnp.ndarray,
    window: Optional[int] = None,
) -> Tuple:
    q, k, v, o, lse = res
    block_q, block_k = blocks
    bh, s, d = q.shape
    bg = k.shape[0]
    sk = k.shape[1]
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
        keepdims=True,
    )

    kernel_args = (q, k, v, do, lse, delta)
    row3 = pl.BlockSpec((1, block_q, d), lambda i, j, jk: (i, j, 0))
    row2 = pl.BlockSpec((1, block_q, 1), lambda i, j, jk: (i, j, 0))
    kv3 = pl.BlockSpec(
        (1, block_k, d),
        lambda i, j, jk: (
            _kv_index(i, h, g),
            _clamped_kv_block(j, jk, block_q, block_k, causal, window),
            0,
        ),
    )
    dq = pl.pallas_call(
        functools.partial(
            _dq_stream_kernel, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, nk=sk // block_k,
            window=window,
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(bh, s // block_q, sk // block_k),
        in_specs=[row3, kv3, kv3, row3, row2, row2],
        out_specs=row3,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*kernel_args)

    # dK/dV per QUERY head (expanded), summed over the group afterwards;
    # grid streams Q blocks on the trailing dimension.  Invalid steps sit
    # BEFORE the first diagonal block (plain causal) and, with a window,
    # also AFTER the band's last q block — hence the two-sided clip in
    # _clamped_q_block.
    nq_s = s // block_q
    q_im = lambda i, jk, jq: (  # noqa: E731
        i, _clamped_q_block(jk, jq, block_q, block_k, causal, nq_s, window), 0
    )
    qrow3 = pl.BlockSpec((1, block_q, d), q_im)
    qrow2 = pl.BlockSpec((1, block_q, 1), q_im)
    kvb = pl.BlockSpec(
        (1, block_k, d), lambda i, jk, jq: (_kv_index(i, h, g), jk, 0)
    )
    out_kvb = pl.BlockSpec((1, block_k, d), lambda i, jk, jq: (i, jk, 0))
    dk_exp, dv_exp = pl.pallas_call(
        functools.partial(
            _dkv_stream_kernel, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, nq=nq_s, window=window,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, sk, d), jnp.float32),
        ),
        grid=(bh, sk // block_k, s // block_q),
        in_specs=[qrow3, kvb, kvb, qrow3, qrow2, qrow2],
        out_specs=(out_kvb, out_kvb),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(*kernel_args)

    r = h // g
    b = bh // h
    dk = dk_exp.reshape(b, g, r, sk, d).sum(axis=2).reshape(bg, sk, d)
    dv = dv_exp.reshape(b, g, r, sk, d).sum(axis=2).reshape(bg, sk, d)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_bwd_resident(
    h: int,
    g: int,
    causal: bool,
    sm_scale: float,
    blocks: Optional[Tuple[int, int]],
    interpret: bool,
    res: Tuple,
    do: jnp.ndarray,
    window: Optional[int] = None,
) -> Tuple:
    q, k, v, o, lse = res
    block_q, block_k = blocks
    bh, s, d = q.shape
    bg = k.shape[0]
    sk = k.shape[1]
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
        keepdims=True,
    )  # [bh, s, 1]

    kernel_args = (q, k, v, do, lse, delta)
    kv_spec = pl.BlockSpec(
        (1, sk, d), lambda i, j: (_kv_index(i, h, g), 0, 0)
    )
    row_spec3 = pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0))
    row_spec2 = pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0))

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, seq_k=sk, window=window,
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(bh, s // block_q),
        in_specs=[row_spec3, kv_spec, kv_spec, row_spec3, row_spec2,
                  row_spec2],
        out_specs=row_spec3,
        interpret=interpret,
    )(*kernel_args)

    # dK/dV per QUERY head (expanded), summed over the group afterwards.
    full_row3 = pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0))
    full_row2 = pl.BlockSpec((1, s, 1), lambda i, j: (i, 0, 0))
    kvb_spec = pl.BlockSpec(
        (1, block_k, d), lambda i, j: (_kv_index(i, h, g), j, 0)
    )
    out_kvb = pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0))
    dk_exp, dv_exp = pl.pallas_call(
        functools.partial(
            _dkv_kernel, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, seq_q=s, window=window,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, sk, d), jnp.float32),
        ),
        grid=(bh, sk // block_k),
        in_specs=[full_row3, kvb_spec, kvb_spec, full_row3, full_row2,
                  full_row2],
        out_specs=(out_kvb, out_kvb),
        interpret=interpret,
    )(*kernel_args)

    r = h // g
    b = bh // h
    dk = dk_exp.reshape(b, g, r, sk, d).sum(axis=2).reshape(bg, sk, d)
    dv = dv_exp.reshape(b, g, r, sk, d).sum(axis=2).reshape(bg, sk, d)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# --------------------------------------------------------------------- #
# public API                                                            #
# --------------------------------------------------------------------- #


# Minimum sequence length at which the PADDED-head kernel (head_dim < 128
# zero-padded to the 128-lane tile) is preferred over dense XLA attention
# by the auto-picker: the MXU pads the lane dim to 128 either way, but the
# kernel's fixed overheads only amortize at the lengths where flash was
# measured faster (resident kernels: 14.5 vs 18.9 ms at seq 2048, 43.8 vs
# 64.7 ms at 4096 fwd+bwd on v5e — BENCH_NOTES.md flash table).  Exact
# 128-multiple heads keep using the kernel at any supported length.
PADDED_HEAD_MIN_SEQ = 2048


def supports(q_shape: Tuple[int, ...], k_shape: Tuple[int, ...],
             block: int = 128) -> bool:
    """Whether shapes meet the kernel's TPU tiling constraints.

    Head dims that are not a 128 multiple are supported up to 128 by
    zero-padding the head dimension to one lane tile (the Llama-1B-class
    ``head_dim=64``): q/k padding adds zero to every score and v padding
    zeros the padded output dims, so the math is exact, and the MXU pads
    the lane dimension to 128 regardless — see :func:`flash_attention`.
    """
    b, s, h, d = q_shape
    g = k_shape[2]
    return (
        (d % 128 == 0 or d < 128)
        and s % block == 0
        and k_shape[1] % block == 0
        and h % g == 0
    )


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
    streaming: Optional[bool] = None,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Fused flash attention.  ``q``: ``[b, s, h, d]``; ``k, v``:
    ``[b, s_k, g, d]`` with ``g`` dividing ``h`` (GQA).  Returns
    ``[b, s, h, d]`` in ``q.dtype``.  Requires ``d % 128 == 0`` or
    ``d < 128`` (the head dim is zero-padded to one 128-lane tile — exact,
    see :func:`supports`) and sequence lengths divisible by the block
    sizes; ``interpret=True`` runs the kernels on any backend for testing.

    ``window`` (requires ``causal``) is Mistral-style sliding-window
    attention: attend iff ``0 <= qpos - kpos < window``.  Every kernel
    variant skips COMPUTE for blocks outside the band (the resident
    loops run ``jk0..diagonal``; the streaming grids clamp their index
    maps on both sides).  HBM traffic scales with the window only in the
    STREAMING variants — the resident kernels still stage the full K/V
    row in VMEM per program — so prefer ``streaming=True`` for
    long-sequence/small-window workloads.

    ``streaming`` selects the third-grid-dimension kernel variants whose
    per-program VMEM is O(block·d) — K/V (and, in the dK/dV kernel, Q/dO)
    tiles stream from HBM instead of residing whole — enabling very long
    single-chip sequences.  ``None`` picks automatically from the K/V row
    footprint.  Under causal masking the streaming grids skip
    fully-masked cells' work: clamped block index maps re-request the
    tile already resident (no HBM copy — Pallas elides same-index
    refetches) and ``pl.when`` skips the compute, so masked cells cost
    one grid bump (see ``_clamped_kv_block``; asserted in
    tests/test_flash_attention.py::test_streaming_causal_skips_masked_fetches).
    """
    b, s, h, d = q.shape
    g = k.shape[2]
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale
    _validate_window(causal, window)
    d_pad = (-d) % 128
    if d_pad:
        if d > 128:
            raise ValueError(
                f"flash_attention requires head_dim % 128 == 0 or "
                f"head_dim < 128 (got {d}); see supports()"
            )
        # Zero-pad head_dim to the 128-lane tile (Mosaic's last-dim tile
        # is always 128; the MXU pads the lane dim to 128 regardless, so
        # the extra MACs are largely free).  Exactness: sm_scale above is
        # computed from the ORIGINAL d; padded q/k dims contribute zero
        # to every score; padded v dims make the extra output dims
        # exactly zero and are sliced off below.  Autodiff through the
        # pad/slice routes gradients back to the unpadded operands.
        widths = ((0, 0), (0, 0), (0, 0), (0, d_pad))
        q = jnp.pad(q, widths)
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
        d = d + d_pad
    if streaming is None:
        # K+V rows of one head resident in the non-streaming kernels, in
        # the input dtype (the per-block f32 cast is transient).
        streaming = (
            2 * k.shape[1] * d * jnp.dtype(k.dtype).itemsize > _STREAM_BYTES
        )
    qr = jnp.transpose(q, (0, 2, 1, 3)).reshape(b * h, s, d)
    kr = jnp.transpose(k, (0, 2, 1, 3)).reshape(b * g, k.shape[1], d)
    vr = jnp.transpose(v, (0, 2, 1, 3)).reshape(b * g, v.shape[1], d)
    o = _flash(
        qr, kr, vr, h, g, causal, sm_scale,
        (min(block_q, s), min(block_k, k.shape[1])), interpret, streaming,
        window,
    )
    o = jnp.transpose(o.reshape(b, h, s, d), (0, 2, 1, 3))
    return o[..., : d - d_pad] if d_pad else o


# --------------------------------------------------------------------- #
# decode: few-query attention against a KV cache                        #
# --------------------------------------------------------------------- #


def _decode_block_k(s: int) -> Optional[int]:
    """Largest standard block size dividing cache length ``s``."""
    return next((c for c in (512, 256, 128) if s % c == 0), None)


def _decode_kernel(
    len_ref: Any,
    q_ref: Any,
    k_ref: Any,
    v_ref: Any,
    *rest: Any,
    g: int,
    r: int,
    hd: int,
    sm_scale: float,
    block_k: int,
    window: Optional[int],
    quant: bool,
) -> None:
    """One (batch, kv-head, K-block) grid cell: ``g*r`` query rows
    against one streamed K/V block, online softmax carried in VMEM
    scratch across the (sequential, innermost) block dimension.

    The live region depends on the RUNTIME cache length (scalar-prefetch
    ``len_ref``): blocks outside it are skipped — ``pl.when`` elides the
    compute and the clamped index maps re-request the resident tile so
    no HBM fetch is issued (the same machinery as the streaming causal
    kernels).  Per-step cost — bandwidth AND compute — follows the
    generated prefix, not the cache allocation.  Forward only (decode
    has no backward).

    Operand layouts are HEAD-FOLDED: Mosaic requires a block's last two
    dims to be (8k, 128k)-tileable or full axes, so a width-1 block over
    a ``nkv`` axis cannot lower (caught on real TPU; interpret mode
    does not enforce tiling).  K/V arrive as ``[1, Bk, hd]`` tiles of a
    ``[b, s, nkv*hd]`` view — the kv head is picked by the index map as
    a lane-axis block offset, so the fetch stays one head's tile.

    ``quant=True``: K/V refs are int8 with f32 per-(position, head)
    scales — dequantized ONE BLOCK AT A TIME in VMEM, so HBM moves half
    the bytes of a bf16 cache (the actual int8-KV bandwidth win; the
    dense path dequantizes the whole cache in HBM first and forfeits
    it).  ``ks_ref``/``vs_ref`` are the head's whole scale row viewed
    ``[1, 1, nkb, Bk]`` (s floats — fetched once per (batch, head), ~s·4
    bytes, negligible next to the K tiles); the current block's row is
    selected with an iota/where reduction because the row index ``jb``
    is a runtime value and Mosaic has no dynamic sublane indexing."""
    if quant:
        ks_ref, vs_ref, o_ref, m_sc, l_sc, acc_sc = rest
    else:
        o_ref, m_sc, l_sc, acc_sc = rest
    jb = pl.program_id(2)
    nkb = pl.num_programs(2)
    length = len_ref[0]
    pos0 = length - g
    rows = g * r
    last = lax.div(length - 1, block_k)
    if window is None:
        first = jnp.int32(0)
    else:
        first = lax.div(
            lax.max(pos0 - window + 1, jnp.int32(0)), block_k
        )

    @pl.when(jb == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    @pl.when((jb >= first) & (jb <= last))
    def _body():
        qb = (
            q_ref[0].reshape(rows, hd).astype(jnp.float32) * sm_scale
        )
        kb = k_ref[0].astype(jnp.float32)   # [Bk, hd]
        vb = v_ref[0].astype(jnp.float32)
        if quant:
            def row_of(sref):
                # [nkb, Bk] → row jb (the fetched K tile's block).
                all_rows = sref[0, 0]
                sel = (
                    lax.broadcasted_iota(jnp.int32, all_rows.shape, 0)
                    == jb
                )
                return jnp.sum(
                    jnp.where(sel, all_rows, 0.0), axis=0
                )

            kb = kb * row_of(ks_ref).reshape(block_k, 1)
            vb = vb * row_of(vs_ref).reshape(block_k, 1)
        s = lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [rows, Bk]
        # Row i is query position pos0 + i // r (r grouped query heads
        # per kv head, consecutive).
        qpos = pos0 + lax.broadcasted_iota(jnp.int32, (rows, 1), 0) // r
        col = jb * block_k + lax.broadcasted_iota(
            jnp.int32, (rows, block_k), 1
        )
        valid = col <= qpos
        if window is not None:
            valid &= col > qpos - window
        s = jnp.where(valid, s, _NEG)
        m_prev = m_sc[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_sc[...] = l_sc[...] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_sc[...] = acc_sc[...] * corr + lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_sc[...] = m_new

    @pl.when(jb == nkb - 1)
    def _finish():
        o_ref[0] = (acc_sc[...] / l_sc[...]).reshape(g, r * hd)


def supports_decode(
    q_shape: Tuple[int, ...], k_shape: Tuple[int, ...],
    window: Optional[int],
) -> bool:
    """Static eligibility for :func:`flash_decode_attention` (the
    auto-dispatch gate in ``models.generation._attend_chunk``): the same
    conditions the kernel entry validates, answered as a bool.  K/V
    stream one block at a time, so there is NO cache-length VMEM cap —
    only tiling/grouping constraints and a floor under which the dense
    read is not worth a kernel dispatch."""
    b, g, nh, hd = q_shape
    s, nkv = k_shape[1], k_shape[2]
    if hd % 128 != 0 or nkv == 0 or nh % nkv != 0:
        return False
    if s < 256 or _decode_block_k(s) is None:
        return False
    return window is None or window >= 1


def flash_decode_attention(
    q: jnp.ndarray,              # [b, g, nh, hd] — rope'd queries at
                                 # consecutive positions pos0..pos0+g-1
    ck: jnp.ndarray,             # [b, max_len, nkv, hd] KV cache
    cv: jnp.ndarray,
    pos0: jnp.ndarray,           # [] int32 — first query's position
    *,
    window: Optional[int] = None,
    block_k: Optional[int] = None,
    k_scale: Optional[jnp.ndarray] = None,  # f32 [b, nkv, max_len]
    v_scale: Optional[jnp.ndarray] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Decode-side flash attention: ``g`` consecutive queries against the
    LIVE PREFIX of a KV cache — the Pallas twin of the dense
    ``models.generation._attend_chunk`` (g=1 is the plain per-token
    decode read; g=γ+1 is speculative verification).

    Unlike the prefill kernels (static causal geometry), the masked
    region here depends on a RUNTIME scalar: the cache is ``max_len``
    rows but only ``pos0+g`` are live.  The length rides in as a
    scalar-prefetch operand, visible to BOTH the block index maps
    (clamped — tiles outside the live/banded region re-request the
    resident tile, so no HBM fetch is issued) and the kernel
    (``pl.when`` skips their compute): per-step bandwidth and FLOPs
    follow the generated length, not the cache allocation.  K/V stream
    one ``[block_k, hd]`` tile at a time, so any ``max_len`` tiles the
    grid can express is supported.  Output is f32 ``[b, g, nh*hd]``,
    numerically the dense path\'s (same f32 accumulation; oracle-tested
    in tests/test_flash_attention.py).

    ``k_scale``/``v_scale`` (both or neither): the cache is int8 with
    per-(position, head) symmetric scales in the QuantKVCache
    ``[b, nkv, max_len]`` layout (positions last = the kernel's lane
    dim, no transpose needed) — dequantized block-wise in VMEM, so the
    HBM side moves int8 bytes."""
    b, g, nh, hd = q.shape
    s, nkv = ck.shape[1], ck.shape[2]
    if nh % nkv != 0:
        raise ValueError(f"nh={nh} not divisible by nkv={nkv}")
    r = nh // nkv
    if block_k is None:
        block_k = _decode_block_k(s)
        if block_k is None:
            raise ValueError(
                f"cache length {s} has no 128/256/512 block divisor; "
                "pass block_k or use the dense path"
            )
    elif s % block_k != 0:
        raise ValueError(f"cache length {s} not divisible by {block_k}")
    if window is not None and window < 1:
        raise ValueError("window must be >= 1")
    quant = k_scale is not None
    if quant != (v_scale is not None):
        raise ValueError("pass both k_scale and v_scale, or neither")
    # Head-folded views (pure reshapes — the head axis is contiguous with
    # hd, so no copy): Mosaic requires a block's last two dims to be
    # (8k, 128k)-tileable or full axes, which a width-1 nkv-axis block is
    # not.  The kv head becomes a lane-axis block offset instead.
    qf = q.reshape(b, g, nh * hd)
    ckf = ck.reshape(b, s, nkv * hd)
    cvf = cv.reshape(b, s, nkv * hd)
    length = jnp.reshape(pos0 + g, (1,)).astype(jnp.int32)
    nkb = s // block_k

    def kv_im(i: Any, h: Any, jb: Any, len_ref: Any) -> Tuple:
        # Clamp into the live (and, with a window, banded) block range:
        # out-of-range grid steps re-request whatever tile the clamp
        # lands on — already resident, so Pallas elides the fetch.
        length = len_ref[0]
        last = lax.div(length - 1, block_k)
        if window is None:
            first = jnp.int32(0)
        else:
            first = lax.div(
                lax.max(length - g - window + 1, jnp.int32(0)), block_k
            )
        return (i, lax.clamp(first, jb, last), h)

    q_im = lambda i, h, jb, len_ref: (i, 0, h)  # noqa: E731
    in_specs = [
        pl.BlockSpec((1, g, r * hd), q_im),
        pl.BlockSpec((1, block_k, hd), kv_im),
        pl.BlockSpec((1, block_k, hd), kv_im),
    ]
    operands = [length, qf, ckf, cvf]
    if quant:
        # One head's whole scale row [nkb, Bk] per (batch, head) cell —
        # s floats, fetched once per (i, h) (the index map is constant
        # over jb, so Pallas elides per-block refetches); full-axis
        # last-two dims keep it tileable for any nkb.
        in_specs += [
            pl.BlockSpec(
                (1, 1, nkb, block_k),
                lambda i, h, jb, len_ref: (i, h, 0, 0),
            ),
        ] * 2
        operands += [
            k_scale.reshape(b, nkv, nkb, block_k),
            v_scale.reshape(b, nkv, nkb, block_k),
        ]
    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, g=g, r=r, hd=hd, sm_scale=hd ** -0.5,
            block_k=block_k, window=window, quant=quant,
        ),
        out_shape=jax.ShapeDtypeStruct((b, g, nh * hd), jnp.float32),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, nkv, nkb),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, g, r * hd), q_im),
            scratch_shapes=[
                pltpu.VMEM((g * r, 1), jnp.float32),
                pltpu.VMEM((g * r, 1), jnp.float32),
                pltpu.VMEM((g * r, hd), jnp.float32),
            ],
        ),
        interpret=interpret,
    )(*operands)
    return out
