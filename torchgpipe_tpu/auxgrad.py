"""Trace-time scale for injected auxiliary gradients.

Layers that inject auxiliary-objective gradients via a custom-vjp identity
(:func:`torchgpipe_tpu.models.moe.add_aux_grad`) run once *per micro-batch*,
while the engines' task loss is reduced over the whole mini-batch — so a
constant injection would multiply the auxiliary coefficient by the number of
micro-batches.  The engines set this trace-time scale to ``1/m`` while
tracing micro-batch cells; injection sites read it when captured into the
trace (same trace-time discipline as the checkpoint phase flags,
:mod:`torchgpipe_tpu.checkpoint`), making the optimized objective
``task_loss + weight * mean_over_microbatches(aux)`` regardless of the
chunk count.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Iterator


class _Scale(threading.local):
    def __init__(self) -> None:
        self.value = 1.0


_scale = _Scale()


def current_aux_scale() -> Any:
    """The scale an aux-gradient injection traced now should apply.

    A python float, or a traced scalar when the weighting is data-dependent
    (the SPMD engine zeroes fill/drain garbage cells at runtime).
    """
    return _scale.value


@contextlib.contextmanager
def aux_scale(value: Any) -> Iterator[None]:
    """Set the trace-time aux-gradient scale (used by the engines)."""
    prev = _scale.value
    _scale.value = value
    try:
        yield
    finally:
        _scale.value = prev
