"""Radix prefix-sharing KV cache: shared system prompts reuse KV slots.

Production request mixes are tenant-shaped: thousands of requests open
with the same system prompt, and recomputing its KV per request burns
prefill FLOPs on tokens whose cache rows are already sitting in the
pool (RadixAttention, arXiv:2312.07104; vLLM's prefix caching).  This
module is the shape-static TPU variant over
:class:`~torchgpipe_tpu.serving.cache_pool.CachePool`, where the page
granularity is a whole slot:

* a **radix trie** indexes the prompts whose KV currently lives in a
  pool slot.  Admission consults it BEFORE prefilling: the longest
  common prefix between the new prompt and any cached prompt names a
  **donor slot** whose rows ``[0, m)`` are exactly the KV a cold
  prefill of those tokens would write (K/V at position ``p`` depend
  only on tokens ``<= p``, and slot-masked decode never rewrites rows
  below a frontier) — so the engine COPIES them with one fixed-shape
  compiled program and prefills only the remainder.  At most
  ``prompt_len - 1`` tokens reuse: the last prompt token always
  prefills, producing the first-token logits.  Reuse is gated BITWISE
  against cold prefill (``tools/fleet_verify.py``).
* **per-slot refcounts** extend the pool's LIFO free list: inserting a
  prompt pins its slot (``pool.retain``), so a donor outlives its
  request and a slot frees only at refcount 0 — a referenced slot can
  NEVER be recycled under another tenant (the refcount invariant the
  churn grid certifies).
* **bounded capacity** — LRU eviction past ``max_entries``, plus
  cooperative :meth:`reclaim` under admission pressure (queued requests
  beat idle cached prefixes to slots).

The trie itself is host-side and O(prompt length) per operation; the
only device work reuse adds is the single ``prefix_copy`` program —
the steady-state program count stays statically bounded
(``Engine.program_count``, certified by ``analysis.serving``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from torchgpipe_tpu.serving.cache_pool import CachePool


@dataclasses.dataclass
class _Entry:
    """One cached prompt: its tokens live in ``slot`` rows [0, len)."""

    tokens: Tuple[int, ...]
    slot: int
    last_used: int


class _Node:
    """Compressed radix-trie node: edges labeled with token runs."""

    __slots__ = ("edges", "entry")

    def __init__(self) -> None:
        # first token of the run -> (full run, child node)
        self.edges: Dict[int, Tuple[Tuple[int, ...], "_Node"]] = {}
        self.entry: Optional[_Entry] = None


def _common_len(a: Sequence[int], b: Sequence[int]) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


class RadixPrefixCache:
    """The trie + pinning policy; attach via ``Engine(prefix_cache=)``.

    ``min_prefix_len`` guards against copying tiny prefixes (the copy
    dispatch has a fixed cost — reusing 2 tokens is not worth it);
    ``max_entries`` bounds how many pool slots the cache may pin.
    """

    def __init__(self, *, min_prefix_len: int = 4,
                 max_entries: int = 2) -> None:
        if min_prefix_len < 1:
            raise ValueError(
                f"min_prefix_len must be >= 1, got {min_prefix_len}"
            )
        if max_entries < 1:
            raise ValueError(
                f"max_entries must be >= 1, got {max_entries}"
            )
        self.min_prefix_len = min_prefix_len
        self.max_entries = max_entries
        self._root = _Node()
        self._entries: Dict[int, _Entry] = {}   # slot -> entry
        self._tick = 0
        self.hits = 0
        self.misses = 0
        self.reused_tokens = 0
        self.evictions = 0

    # ------------------------------------------------------------------ #
    # trie mechanics                                                     #
    # ------------------------------------------------------------------ #

    def __len__(self) -> int:
        return len(self._entries)

    def entries(self) -> List[_Entry]:
        return list(self._entries.values())

    def _any_entry(self, node: _Node) -> Optional[_Entry]:
        """Some entry at or below ``node`` — every one shares the path's
        prefix, so any of them is a valid donor."""
        if node.entry is not None:
            return node.entry
        for _, (_, child) in sorted(node.edges.items()):
            got = self._any_entry(child)
            if got is not None:
                return got
        return None

    def match(self, prompt: Any,
              limit: Optional[int] = None) -> Tuple[int, Optional[int]]:
        """Longest cached prefix of ``prompt``: ``(m, donor_slot)``.

        ``m`` is capped at ``limit`` (the engine passes
        ``prompt_len - 1``) and zeroed below ``min_prefix_len`` — a
        short match reports as a miss.  A hit refreshes the donor
        entry's LRU stamp."""
        toks = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if limit is not None:
            toks = toks[:max(limit, 0)]
        node, depth = self._root, 0
        best: Tuple[int, Optional[_Node]] = (0, None)
        while toks[depth:]:
            edge = node.edges.get(toks[depth])
            if edge is None:
                break
            run, child = edge
            k = _common_len(run, toks[depth:])
            depth += k
            if k < len(run):
                # Ended mid-edge: the prefix continues into this run —
                # any entry below ``child`` shares prompt[:depth].
                best = (depth, child)
                break
            node = child
            best = (depth, node)
        m, at = best
        if m < self.min_prefix_len or at is None:
            self.misses += 1
            return 0, None
        entry = self._any_entry(at)
        if entry is None:       # pragma: no cover — structural invariant
            self.misses += 1
            return 0, None
        self._tick += 1
        entry.last_used = self._tick
        self.hits += 1
        self.reused_tokens += m
        return m, entry.slot

    def insert(self, prompt: Any, slot: int, pool: CachePool) -> bool:
        """Index ``prompt`` as living in ``slot`` and PIN the slot
        (``pool.retain``).  Returns False (no pin) when the prompt is
        shorter than ``min_prefix_len``, the slot already donates, or an
        existing entry's prompt already covers this one (prefix of a
        cached prompt — nothing new to index).  May LRU-evict to stay
        within ``max_entries``."""
        toks = tuple(int(t) for t in np.asarray(prompt).reshape(-1))
        if len(toks) < self.min_prefix_len or slot in self._entries:
            return False
        covered, _ = self._lookup_exact_cover(toks)
        if covered:
            return False
        self._tick += 1
        entry = _Entry(tokens=toks, slot=slot, last_used=self._tick)
        self._insert_node(toks, entry)
        self._entries[slot] = entry
        pool.retain(slot)
        while len(self._entries) > self.max_entries:
            self._evict_lru(pool)
        return True

    def _lookup_exact_cover(
        self, toks: Tuple[int, ...]
    ) -> Tuple[bool, int]:
        """Is ``toks`` a prefix of (or equal to) a cached prompt?"""
        node, depth = self._root, 0
        while toks[depth:]:
            edge = node.edges.get(toks[depth])
            if edge is None:
                return False, depth
            run, child = edge
            k = _common_len(run, toks[depth:])
            depth += k
            if k == len(run):
                node = child
                continue
            # mid-edge: covered iff the whole remainder matched
            return depth == len(toks), depth
        return True, depth

    def _insert_node(self, toks: Tuple[int, ...], entry: _Entry) -> None:
        node, depth = self._root, 0
        while True:
            rest = toks[depth:]
            if not rest:
                node.entry = entry
                return
            edge = node.edges.get(rest[0])
            if edge is None:
                leaf = _Node()
                leaf.entry = entry
                node.edges[rest[0]] = (rest, leaf)
                return
            run, child = edge
            k = _common_len(run, rest)
            if k == len(run):
                node = child
                depth += k
                continue
            # split the edge at k
            mid = _Node()
            mid.edges[run[k]] = (run[k:], child)
            node.edges[rest[0]] = (run[:k], mid)
            node = mid
            depth += k

    def _remove_node(self, toks: Tuple[int, ...]) -> None:
        """Unlink the entry stored exactly at ``toks`` (path compression
        of emptied nodes is skipped — the trie is bounded by
        ``max_entries`` live prompts, so stranded interior nodes are a
        few dozen tuples at most)."""
        node, depth = self._root, 0
        parents: List[Tuple[_Node, int]] = []
        while toks[depth:]:
            edge = node.edges.get(toks[depth])
            if edge is None:
                return
            run, child = edge
            parents.append((node, toks[depth]))
            node = child
            depth += len(run)
        node.entry = None
        while parents and node.entry is None and not node.edges:
            parent, first = parents.pop()
            del parent.edges[first]
            node = parent

    # ------------------------------------------------------------------ #
    # eviction                                                           #
    # ------------------------------------------------------------------ #

    def _evict_entry(self, entry: _Entry, pool: CachePool) -> None:
        self._remove_node(entry.tokens)
        del self._entries[entry.slot]
        pool.release(entry.slot)
        self.evictions += 1

    def _evict_lru(self, pool: CachePool) -> None:
        victim = min(self._entries.values(), key=lambda e: e.last_used)
        self._evict_entry(victim, pool)

    def reclaim(self, pool: CachePool, want: int = 1) -> int:
        """Admission pressure valve: evict up to ``want`` IDLE entries —
        ones whose pin is the slot's only remaining reference, so
        eviction actually frees a slot (an entry whose request still
        runs is skipped; evicting it would free nothing).  Returns the
        number of slots freed."""
        freed = 0
        for entry in sorted(self._entries.values(),
                            key=lambda e: e.last_used):
            if freed >= want:
                break
            if pool.refcount(entry.slot) == 1 and (
                pool.owner_of(entry.slot) is None
            ):
                self._evict_entry(entry, pool)
                freed += 1
        return freed

    def clear(self, pool: CachePool) -> None:
        """Drop every entry (and its pin) — e.g. before a drain."""
        for entry in list(self._entries.values()):
            self._evict_entry(entry, pool)

    def stats(self) -> Dict[str, int]:
        return {
            "entries": len(self._entries),
            "hits": self.hits,
            "misses": self.misses,
            "reused_tokens": self.reused_tokens,
            "evictions": self.evictions,
        }


__all__ = ["RadixPrefixCache"]
