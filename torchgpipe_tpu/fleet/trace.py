"""Deterministic synthetic request traces: the fleet's measurement fuel.

Fleet claims — prefix-reuse hit-rate → TTFT drop, failover recovery,
speculation acceptance → TPOT drop — mean nothing against a hand-picked
burst of four requests.  This module generates production-SHAPED load,
seeded and reproducible, scalable to a million requests without
materializing them (a lazy generator):

* **ragged lengths** — prompt and generation budgets drawn per request
  from configured ranges (uniform), the shape continuous batching and
  the prefill bucket ladder exist for;
* **bursty arrivals** — a two-state Markov-modulated Poisson process
  (burst/calm states with separate rates, geometric dwell times): the
  arrival pattern that makes queue-wait percentiles interesting;
* **shared-prefix tenants** — each tenant owns a fixed system prompt
  (its length drawn once per tenant) prepended to every one of its
  requests, with tenant popularity following a Zipf-ish skew — the
  workload a radix prefix cache exists for;
* **sessions** — a fraction of requests continue an existing tenant
  session (router affinity food).

Determinism: the stream is a pure function of ``TraceConfig`` (one
``numpy.random.RandomState(seed)`` consumed sequentially), so two walks
of the same config are identical — replay IS re-generation.

Honesty contract (the "no silent caps" acceptance rule): a request
whose prompt + budget cannot fit ``max_len`` is never silently
resized — :func:`synthetic_trace` SKIPS it and counts it in
``TraceStats.skipped_too_long``, and every consumer is expected to
surface that count (``bench.py --fleet`` refuses to publish a run
whose stats it didn't log).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class TraceRequest:
    """One synthetic request."""

    index: int
    arrival_s: float
    tenant: int
    session: str
    prompt: np.ndarray           # [s] int32 = tenant prefix + suffix
    prefix_len: int              # tokens shared with the whole tenant
    max_new_tokens: int


@dataclasses.dataclass
class TraceStats:
    """What the generator produced — and what it refused to."""

    generated: int = 0
    skipped_too_long: int = 0
    burst_arrivals: int = 0
    total_prompt_tokens: int = 0
    shared_prefix_tokens: int = 0
    burst_prompt_tokens: int = 0
    last_arrival_s: float = 0.0
    per_tenant: Dict[int, int] = dataclasses.field(default_factory=dict)

    @property
    def shareable_fraction(self) -> float:
        """Fraction of prompt tokens inside a tenant prefix — the
        prefix cache's theoretical reuse ceiling on this trace."""
        if not self.total_prompt_tokens:
            return 0.0
        return self.shared_prefix_tokens / self.total_prompt_tokens


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    """Knobs for :func:`synthetic_trace`; defaults make a small, CPU-
    friendly mix (scale ``n_requests`` to millions — generation is
    lazy and O(prompt length) per request)."""

    n_requests: int
    seed: int = 0
    vocab: int = 64
    n_tenants: int = 4
    # Tenant shared-prefix lengths drawn once per tenant from this range
    # (inclusive); tenant popularity ~ 1/rank (Zipf-ish).
    prefix_len: Tuple[int, int] = (6, 12)
    # Per-request unique suffix length range (inclusive; >= 1 so the
    # full prompt is never exactly the bare tenant prefix).
    suffix_len: Tuple[int, int] = (1, 8)
    new_tokens: Tuple[int, int] = (2, 12)
    # Requests must fit prompt + budget <= max_len (the pool contract);
    # misfits are SKIPPED AND COUNTED, never resized silently.
    max_len: int = 64
    # Markov-modulated Poisson arrivals: mean inter-arrival seconds per
    # state, and the per-arrival probability of switching state.
    calm_gap_s: float = 0.05
    burst_gap_s: float = 0.002
    p_enter_burst: float = 0.1
    p_exit_burst: float = 0.3
    # Fraction of requests that continue an existing tenant session.
    p_continue_session: float = 0.3
    # Burst-state length overrides (None = bursts change ONLY arrival
    # timing, the pre-disaggregation behaviour — traces generated under
    # old configs stay byte-identical).  Set to shift the burst state's
    # suffix-length / generation-budget ranges, e.g. long-prompt
    # prefill storms over a short-prompt base load — the mix
    # phase-disaggregated serving exists for.
    burst_suffix_len: Optional[Tuple[int, int]] = None
    burst_new_tokens: Optional[Tuple[int, int]] = None


def tenant_prefixes(cfg: TraceConfig) -> List[np.ndarray]:
    """Each tenant's fixed system prompt (deterministic per config) —
    drawn from a DEDICATED stream so callers can reconstruct them
    without walking the trace."""
    rng = np.random.RandomState(cfg.seed ^ 0x7E7A17)
    out: List[np.ndarray] = []
    lo, hi = cfg.prefix_len
    for _ in range(cfg.n_tenants):
        n = int(rng.randint(lo, hi + 1))
        out.append(rng.randint(0, cfg.vocab, (n,)).astype(np.int32))
    return out


def synthetic_trace(
    cfg: TraceConfig,
    stats: Optional[TraceStats] = None,
) -> Iterator[TraceRequest]:
    """Lazily yield ``cfg.n_requests`` seeded requests (see the module
    docstring for the shape).  Pass a :class:`TraceStats` to collect
    the honesty counters while streaming."""
    rng = np.random.RandomState(cfg.seed)
    prefixes = tenant_prefixes(cfg)
    # Zipf-ish popularity: tenant k with weight 1/(k+1).
    weights = np.array(
        [1.0 / (k + 1) for k in range(cfg.n_tenants)], np.float64
    )
    weights /= weights.sum()
    now = 0.0
    burst = False
    sessions: List[Tuple[int, str]] = []   # (tenant, session id)
    emitted = 0
    attempt = 0
    while emitted < cfg.n_requests:
        attempt += 1
        # arrival process
        if burst:
            gap_mean = cfg.burst_gap_s
            if rng.rand() < cfg.p_exit_burst:
                burst = False
        else:
            gap_mean = cfg.calm_gap_s
            if rng.rand() < cfg.p_enter_burst:
                burst = True
        now += float(rng.exponential(gap_mean))
        # tenant + session
        tenant = int(rng.choice(cfg.n_tenants, p=weights))
        if sessions and rng.rand() < cfg.p_continue_session:
            tenant, session = sessions[int(rng.randint(len(sessions)))]
        else:
            session = f"t{tenant}-s{attempt}"
            sessions.append((tenant, session))
            if len(sessions) > 64:      # bounded memory at 1e6 requests
                sessions.pop(0)
        prefix = prefixes[tenant]
        s_lo, s_hi = cfg.suffix_len
        n_lo, n_hi = cfg.new_tokens
        if burst:
            if cfg.burst_suffix_len is not None:
                s_lo, s_hi = cfg.burst_suffix_len
            if cfg.burst_new_tokens is not None:
                n_lo, n_hi = cfg.burst_new_tokens
        suffix_n = int(rng.randint(s_lo, s_hi + 1))
        suffix = rng.randint(0, cfg.vocab, (suffix_n,)).astype(np.int32)
        prompt = np.concatenate([prefix, suffix])
        new = int(rng.randint(n_lo, n_hi + 1))
        if prompt.size + new > cfg.max_len:
            # The honesty rule: count, never silently shrink.
            if stats is not None:
                stats.skipped_too_long += 1
            continue
        req = TraceRequest(
            index=emitted,
            arrival_s=now,
            tenant=tenant,
            session=session,
            prompt=prompt,
            prefix_len=int(prefix.size),
            max_new_tokens=new,
        )
        if stats is not None:
            # Counted AFTER the skip check: burst_arrivals shares
            # generated's population, so burst_fraction stays <= 1
            # under heavy skipping.
            if burst:
                stats.burst_arrivals += 1
                stats.burst_prompt_tokens += int(prompt.size)
            stats.generated += 1
            stats.total_prompt_tokens += int(prompt.size)
            stats.shared_prefix_tokens += int(prefix.size)
            stats.last_arrival_s = now
            stats.per_tenant[tenant] = (
                stats.per_tenant.get(tenant, 0) + 1
            )
        emitted += 1
        yield req


def prefill_heavy_config(
    n_requests: int,
    seed: int = 0,
    max_len: int = 64,
    **overrides: object,
) -> TraceConfig:
    """The disaggregation stress mix: a short-prompt, decode-dominated
    base load punctuated by bursts of LONG prompts with small budgets —
    prefill storms.  On a unified fleet every storm steals decode
    iterations from in-flight streams (TPOT spikes); a phase-split
    fleet absorbs it in the prefill pool (``bench.py --disagg``
    measures exactly this).  Deterministic per (n_requests, seed,
    max_len); keyword overrides replace any field."""
    burst_lo = max_len // 2
    cfg = dict(
        n_requests=n_requests,
        seed=seed,
        max_len=max_len,
        prefix_len=(4, 6),
        suffix_len=(1, 4),
        new_tokens=(6, 12),
        burst_suffix_len=(burst_lo, max(burst_lo, max_len - 14)),
        burst_new_tokens=(2, 4),
        p_enter_burst=0.15,
        p_exit_burst=0.35,
    )
    cfg.update(overrides)
    return TraceConfig(**cfg)  # type: ignore[arg-type]


def trace_summary(cfg: TraceConfig,
                  sample: int = 2048) -> Dict[str, float]:
    """Cheap summary of a config by walking ``sample`` requests — for
    logging next to bench numbers."""
    stats = TraceStats()
    for _ in synthetic_trace(
        dataclasses.replace(cfg, n_requests=min(cfg.n_requests, sample)),
        stats,
    ):
        pass
    denom = max(stats.generated, 1)
    return {
        "requests": float(stats.generated),
        "skipped_too_long": float(stats.skipped_too_long),
        "shareable_fraction": stats.shareable_fraction,
        "burst_fraction": stats.burst_arrivals / denom,
        "mean_arrival_gap_s": (
            stats.last_arrival_s / denom
        ),
    }


__all__ = [
    "TraceConfig",
    "TraceRequest",
    "TraceStats",
    "prefill_heavy_config",
    "synthetic_trace",
    "tenant_prefixes",
    "trace_summary",
]
