"""Cross-pool KV migration: the handoff of phase-disaggregated serving.

DistServe/Splitwise split the serving fleet by phase — prefill replicas
batch-hungry and compute-bound, decode replicas latency-critical — so a
request LIVES on two replicas: it prefills (and emits its first token)
on a prefill replica, then its KV rows + frontier ship to a decode
replica that continues the stream.  This module is that shipment.

The contract, in the repo's exactness style:

* **Bitwise** — greedy decode is prefix-deterministic and a slot's
  prefill writes are replica-independent, so the decode replica's
  continuation equals an undisturbed unified run token for token
  (``tools/disagg_verify.py`` gates it; the same property the
  drain/restore path already relies on).
* **Fixed-shape** — the payload is one slot's per-layer KV rows (+ int8
  scale rows) with the slot axis sliced away
  (:meth:`~torchgpipe_tpu.serving.engine.Engine.export_kv_rows`), and
  the decode engine writes them through its single ``migrate_ingest``
  program — dst/n are traced values, so EVERY migration reuses one
  compiled program (``analysis.serving.certify_disagg`` proves the
  per-role program count).
* **Two transports, one program** — in-process fleets hand the donor's
  device views straight to the ingest program (zero host copy, the
  ``prefix_copy`` flavor); cross-process fleets stage the same pytree
  as host numpy first (:func:`stage_rows`, the drain-schema snapshot
  flavor).  The ingest program cannot tell the difference.

Failure stays safe by ORDER: the ingest dispatch completes (and blocks
until ready) before :meth:`complete_migration` frees the donor slot —
an ingest that raises (e.g. decode pool full) leaves the donor intact
for the router to re-park and retry.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from torchgpipe_tpu.serving.engine import Engine
from torchgpipe_tpu.serving.scheduler import Request


class MigrationError(RuntimeError):
    """A KV migration handoff could not be performed."""


def _flat_specs(specs: Dict[str, Any]) -> List[Tuple[str, int, Any, str]]:
    return [
        (name, i, tuple(s.shape), str(s.dtype))
        for name, leaves in sorted(specs.items())
        for i, s in enumerate(leaves)
    ]


def validate_pools(src: Engine, dst: Engine) -> None:
    """Didactic compatibility check between a prefill and a decode
    engine: roles correct, equal ``max_len``, and bit-identical per-slot
    KV row signatures (same cfg, same ``kv_quant``/dtype) — the rows one
    exports must be exactly what the other's ingest program expects.
    The router runs this once per prefill×decode pair at construction,
    so an incompatible fleet fails at build time, not mid-handoff."""
    if src.role != "prefill":
        raise MigrationError(
            f"migration source must be a prefill-role engine, got "
            f"role={src.role!r}"
        )
    if dst.role != "decode":
        raise MigrationError(
            f"migration target must be a decode-role engine, got "
            f"role={dst.role!r}"
        )
    if src.pool.max_len != dst.pool.max_len:
        raise MigrationError(
            f"pool max_len differs across roles ({src.pool.max_len} vs "
            f"{dst.pool.max_len}) — a migrated slot's rows must land at "
            "the same positions, so a disaggregated fleet needs equal "
            "max_len everywhere"
        )
    a, b = _flat_specs(src.kv_row_specs()), _flat_specs(dst.kv_row_specs())
    if a != b:
        diff = next(
            (f"{x} vs {y}" for x, y in zip(a, b) if x != y),
            f"{len(a)} vs {len(b)} row leaves",
        )
        raise MigrationError(
            "prefill/decode pools are migration-incompatible: per-slot "
            f"KV row specs differ ({diff}) — build both roles with the "
            "same cfg, max_len, kv_quant and cache dtype"
        )


def stage_rows(rows: Dict[str, Any]) -> Dict[str, Any]:
    """Materialise a migration payload as host numpy arrays — the
    cross-process (drain-schema snapshot) transport.  In-process fleets
    skip this and feed the donor's device views to the ingest program
    zero-copy; the staged pytree has identical structure, shapes and
    bits, so the compiled program serves both transports."""
    return {
        name: [np.asarray(x) for x in leaves]
        for name, leaves in rows.items()
    }


def migrate(
    src: Engine,
    dst: Engine,
    req: Request,
    *,
    on_token: Optional[Callable[[str, int], None]] = None,
    stage_host: bool = False,
) -> str:
    """Hand ONE migration-parked request from ``src`` to ``dst``.

    ``req`` must come from :meth:`Engine.take_migration_ready` (status
    ``'migrating'``, exactly one emitted token — the first token samples
    on the prefill replica so prefill and decode share one sampling-site
    semantics).  ``on_token`` replaces the request's callback on the
    decode side (the router re-wires its recording callback here);
    ``stage_host=True`` forces the drain-schema transport even in
    process.  Raises ``RuntimeError`` when ``dst`` has no free slot —
    the donor is left intact for a retry.  Returns the rid."""
    if req.status != "migrating":
        raise MigrationError(
            f"request {req.rid!r} is {req.status!r}, not parked for "
            "migration — only take_migration_ready() output migrates"
        )
    if len(req.generated) != 1:
        raise MigrationError(
            f"request {req.rid!r} carries {len(req.generated)} emitted "
            "tokens; a prefill engine parks at exactly one"
        )
    rows = src.export_kv_rows(req)
    if stage_host:
        rows = stage_rows(rows)
    dst.ingest_migration(
        rid=req.rid,
        prompt=req.prompt,
        max_new_tokens=req.max_new_tokens,
        rows=rows,
        last_token=req.generated[-1],
        eos_id=req.eos_id,
        on_token=on_token if on_token is not None else req.on_token,
        emitted_prefix=req.emitted_prefix,
    )
    # Ingest succeeded (the dispatch blocked until the device write
    # completed) — only now may the donor slot go.
    src.complete_migration(req)
    return req.rid


__all__ = ["MigrationError", "migrate", "stage_rows", "validate_pools"]
