"""Replica router: session affinity, power-of-two-choices, failover.

One :class:`~torchgpipe_tpu.serving.engine.Engine` is one set of slots
on one set of chips.  The "millions of users" direction needs the layer
above it — N replicas behind one submit() — and that layer's three
problems are exactly this module:

* **Placement** — `power of two choices <https://ieeexplore.ieee.org/
  document/963420>`_ (Mitzenmacher): sample two replicas, route to the
  less loaded — near-best-of-N balance at O(1) probes.  Load is read
  from the shared :class:`~torchgpipe_tpu.obs.MetricsRegistry`: the
  router maintains a ``fleet_occupancy{replica=...}`` gauge per replica
  and tie-breaks on the per-replica ``serving_tpot_seconds`` p50 — the
  same series an external autoscaler would scrape.  ``session=`` pins a
  conversation to its replica (KV locality: later turns reuse the
  replica whose prefix cache holds their history).
* **Failover** — a replica dying mid-generation must not lose its
  in-flight requests.  The router rides the resilience path that
  already exists: a snapshot in the :meth:`Engine.drain` schema
  (cooperative drain when the engine can still run, rebuilt from the
  router's own streamed-token records when it cannot — byte-identical
  schema either way) feeds :meth:`Engine.restore_requests`, and the
  requests resume on a SURVIVING replica, teacher-forced to their last
  emitted token.  Greedy decode is prefix-deterministic, so the resumed
  streams are bitwise what an undisturbed run produces — the killer
  demo ``tools/fleet_verify.py`` gates.
* **Drain-aware scale-down** — :meth:`drain_replica` is the same path
  minus the death: cooperative drain through the engine's
  CheckpointManager hook, restore elsewhere, mark the replica out of
  rotation.

Death in tests is cooperative and deterministic:
``resilience.faults.inject(die_at_step=(replica, step))`` makes the
router raise :class:`ReplicaDied` before that replica's engine step
``step`` — mid-generation when ``step`` lands inside a burst.  A
:class:`~torchgpipe_tpu.obs.flightrec.FlightRecorder` wired in records
every route/failover/drain as a flight event, so a dead replica is a
named edge in the dump, not a mystery.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from torchgpipe_tpu.fleet import migration as _migration
from torchgpipe_tpu.resilience import faults
from torchgpipe_tpu.serving.engine import Engine
from torchgpipe_tpu.serving.scheduler import Request


class ReplicaDied(RuntimeError):
    """A replica stopped serving (fault injection or a real crash
    surfaced by its engine step)."""

    def __init__(self, name: str, reason: str = "died") -> None:
        super().__init__(f"replica {name!r} {reason}")
        self.name = name
        self.reason = reason


@dataclasses.dataclass
class RouterRecord:
    """The router's own view of one request — enough to rebuild a
    drain-schema snapshot even when the owning replica is gone."""

    rid: str
    prompt: np.ndarray
    max_new_tokens: int
    eos_id: Optional[int]
    replica: str
    session: Optional[str] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    on_token: Optional[Callable[[str, int], None]] = None
    moves: int = 0          # failover/drain resubmissions
    # QoS identity (serving/qos.py): carried through every failover /
    # drain resubmission, so a request keeps its latency class and its
    # tenant keeps being charged wherever the request lands.  ``tier``
    # tracks the EFFECTIVE tier (an over-budget demotion sticks here
    # via the drain snapshot, so a migrated request does not silently
    # re-promote).
    tier: str = "standard"
    tenant: Optional[str] = None

    @property
    def done(self) -> bool:
        return len(self.tokens) >= self.max_new_tokens or (
            self.eos_id is not None
            and bool(self.tokens)
            and self.tokens[-1] == self.eos_id
        )


@dataclasses.dataclass
class Replica:
    """One engine behind the router.

    ``degraded`` is the SLO layer's verdict (:mod:`torchgpipe_tpu.obs.
    slo`): the replica is alive and could serve, but its burn-rate
    alert is (or recently was) firing, so it is held out of
    power-of-two-choices rotation until its windows come back clean —
    the serving mirror of ``ReplanOnDrift`` acting on measured drift.
    """

    name: str
    engine: Engine
    alive: bool = True
    draining: bool = False
    degraded: bool = False

    @property
    def in_rotation(self) -> bool:
        return self.alive and not self.draining and not self.degraded


class Router:
    """Route requests over N engine replicas; see the module docstring.

    ``replicas`` maps name -> built :class:`Engine`.  For the shared-
    registry load series, build each engine with
    ``registry=shared.labeled(replica=name)`` (the
    :meth:`~torchgpipe_tpu.obs.MetricsRegistry.labeled` view) and pass
    the same ``registry=shared`` here; without one the router keeps a
    private registry and the gauges are still maintained (just not
    shared with anything else).
    """

    def __init__(
        self,
        replicas: Dict[str, Engine],
        *,
        registry: Optional[Any] = None,
        seed: int = 0,
        session_affinity: bool = True,
        recorder: Optional[Any] = None,
        slo: Optional[Any] = None,
        slo_min_in_rotation: int = 1,
        slo_cooldown_s: float = 0.0,
    ) -> None:
        if not replicas:
            raise ValueError("a router needs at least one replica")
        self.replicas: Dict[str, Replica] = {
            name: Replica(name=name, engine=eng)
            for name, eng in replicas.items()
        }
        # Phase roles (disaggregated serving): the fleet is either all
        # unified or a prefill pool + a decode pool — a mixed fleet
        # would make placement ambiguous (may a unified replica take
        # admissions? migrations?), so it is refused didactically.
        self.roles: Dict[str, str] = {
            name: getattr(eng, "role", "unified")
            for name, eng in replicas.items()
        }
        role_set = set(self.roles.values())
        self.disaggregated = role_set != {"unified"}
        if self.disaggregated:
            if "unified" in role_set:
                raise ValueError(
                    "mixed fleet: unified replicas cannot serve beside "
                    "prefill/decode pools — build the whole fleet one "
                    "way or the other"
                )
            if role_set != {"prefill", "decode"}:
                missing = {"prefill", "decode"} - role_set
                raise ValueError(
                    f"disaggregated fleet needs both pools; missing "
                    f"{sorted(missing)} — every admission prefills in "
                    "the prefill pool and decodes in the decode pool"
                )
        self.pools: Dict[str, List[str]] = {}
        for name, role in self.roles.items():
            self.pools.setdefault(role, []).append(name)
        if self.disaggregated:
            # Fail an incompatible fleet at BUILD time, not mid-handoff:
            # every prefill replica must be able to migrate to every
            # decode replica (same cfg/max_len/kv layout).
            for p in self.pools["prefill"]:
                for d in self.pools["decode"]:
                    _migration.validate_pools(
                        replicas[p], replicas[d]
                    )
        if registry is None:
            from torchgpipe_tpu.obs.registry import MetricsRegistry

            registry = MetricsRegistry()
        self.registry = registry
        self.recorder = recorder
        self.session_affinity = session_affinity
        self._rng = np.random.RandomState(seed)
        self._sessions: Dict[str, str] = {}
        self._records: Dict[str, RouterRecord] = {}
        self._rid_counter = 0
        # Per-replica productive engine steps, owned by the ROUTER —
        # the die_at_step fault hook keys on this, so death timing is
        # a property of the replica's own progress, independent of how
        # callers share ServingMetrics instances across replicas.
        self._replica_steps: Dict[str, int] = {
            name: 0 for name in replicas
        }
        # Replicas whose Engine.drain() the router itself is running
        # (failover / drain_replica): their drain hook must not fire a
        # SECOND resubmission on top of the one those paths do.
        self._router_drains: set = set()
        for name in replicas:
            self.replicas[name].engine.drain_hooks.append(
                self._drain_hook_for(name)
            )
        self._g_occupancy = registry.gauge(
            "fleet_occupancy",
            help="per-replica load: (active + queued) / slots",
            labels=("replica",),
        )
        self._c_routed = registry.counter(
            "fleet_routed_requests", help="requests placed",
            labels=("replica",),
        )
        self._c_failovers = registry.counter(
            "fleet_failovers", help="replica deaths failed over")
        self._c_moved = registry.counter(
            "fleet_moved_requests",
            help="in-flight requests resumed on another replica")
        self._c_migrations = registry.counter(
            "fleet_migrations",
            help="prefill→decode KV handoffs at prompt completion")
        # SLO observe->act wiring (obs.slo.SloMonitor): the router
        # ticks the monitor once per step() and acts on its verdicts —
        # a breaching replica is degraded out of rotation (in-flight
        # requests drained onto survivors), a clean one re-admitted
        # after the cooldown.  ``slo_min_in_rotation`` is the brake:
        # the SLO layer may never evict the last healthy replica
        # (degrading the whole fleet to protect latency serves nobody).
        self.slo = slo
        self.slo_min_in_rotation = int(slo_min_in_rotation)
        self.slo_cooldown_s = float(slo_cooldown_s)
        self._degraded_at: Dict[str, float] = {}
        self._clock: Callable[[], float] = getattr(
            registry, "clock", time.monotonic
        )
        self._c_slo_evicted = registry.counter(
            "fleet_slo_evictions",
            help="replicas degraded out of rotation by a burn-rate "
                 "alert", labels=("replica",),
        )
        self._c_slo_readmitted = registry.counter(
            "fleet_slo_readmissions",
            help="degraded replicas re-admitted after recovery",
            labels=("replica",),
        )
        self._g_degraded = registry.gauge(
            "fleet_degraded",
            help="1 while a replica is held out of rotation by the "
                 "SLO layer", labels=("replica",),
        )

    # ------------------------------------------------------------------ #
    # placement                                                          #
    # ------------------------------------------------------------------ #

    def _record_event(self, kind: str, detail: str = "",
                      rid: Optional[str] = None) -> None:
        if self.recorder is not None:
            self.recorder.record(kind, detail=detail, rid=rid)

    def _update_load_gauges(self) -> None:
        for rep in self.replicas.values():
            eng = rep.engine
            load = (
                len(eng.scheduler.active) + len(eng.scheduler.queue)
            ) / max(eng.pool.num_slots, 1)
            self._g_occupancy.set(load, replica=rep.name)

    def _load(self, name: str) -> Tuple[float, float]:
        """(occupancy gauge, TPOT p50 tiebreak) for one replica, read
        back from the registry series the router maintains — the same
        numbers a scrape sees."""
        occ = self._g_occupancy.value(replica=name)
        tpot = 0.0
        hist = self.registry.get("serving_tpot_seconds")
        if hist is not None and "replica" in getattr(
            hist, "label_names", ()
        ):
            got = hist.percentile(0.5, replica=name)
            tpot = got if got is not None else 0.0
        return float(occ), float(tpot)

    def pick_replica(
        self, session: Optional[str] = None,
        role: Optional[str] = None,
    ) -> str:
        """Power-of-two-choices over in-rotation replicas (session
        affinity first, when enabled and the pinned replica survives).

        In a disaggregated fleet the pick is POOL-scoped: admissions
        and resumptions default to the prefill pool (every entry into
        the fleet prefills first), and session pins bind only the
        DECODE placement — sessions re-prefill anywhere, but their
        multi-turn continuation rows live in one decode replica's pool,
        so a pin names a decode replica and prefill picks neither read
        nor write it."""
        if role is None and self.disaggregated:
            role = "prefill"
        pool = (
            [r.name for r in self.replicas.values()]
            if role is None else self.pools.get(role, [])
        )
        live = [n for n in pool if self.replicas[n].in_rotation]
        if not live:
            what = f"{role} replica" if role else "replica"
            raise ReplicaDied("<all>", f"no {what} in rotation")
        pin_applies = session is not None and (
            not self.disaggregated or role == "decode"
        )
        if (
            pin_applies
            and self.session_affinity
            and self._sessions.get(session) in live
        ):
            return self._sessions[session]
        self._update_load_gauges()
        if len(live) == 1:
            choice = live[0]
        else:
            i, j = self._rng.choice(len(live), size=2, replace=False)
            a, b = live[int(i)], live[int(j)]
            choice = min(a, b, key=self._load)
        if pin_applies:
            self._sessions[session] = choice
        return choice

    def _decode_target(self, session: Optional[str]) -> Optional[str]:
        """The decode replica to ingest one parked request: the
        session-pinned replica when its pin survives (waiting for ITS
        slot preserves multi-turn KV locality), else power-of-two-
        choices over decode replicas WITH a free slot (ingest cannot
        queue — the KV payload needs a slot now).  ``None`` means the
        pool is momentarily full: re-park and retry next step (decode
        progresses every step, so slots free up — no deadlock).
        Raises :class:`ReplicaDied` when no decode replica is in
        rotation at all."""
        live = [
            n for n in self.pools.get("decode", ())
            if self.replicas[n].in_rotation
        ]
        if not live:
            raise ReplicaDied("<all>", "no decode replica in rotation")
        if session is not None and self.session_affinity:
            pinned = self._sessions.get(session)
            if pinned in live:
                if self.replicas[pinned].engine.pool.num_free > 0:
                    return pinned
                return None      # wait for the pinned replica's slot
        free = [
            n for n in live
            if self.replicas[n].engine.pool.num_free > 0
        ]
        if not free:
            return None
        self._update_load_gauges()
        if len(free) == 1:
            choice = free[0]
        else:
            i, j = self._rng.choice(len(free), size=2, replace=False)
            choice = min(free[int(i)], free[int(j)], key=self._load)
        if session is not None and self.session_affinity:
            self._sessions[session] = choice
        return choice

    # ------------------------------------------------------------------ #
    # request API                                                        #
    # ------------------------------------------------------------------ #

    def submit(
        self,
        prompt: Any,
        max_new_tokens: int,
        *,
        rid: Optional[str] = None,
        session: Optional[str] = None,
        eos_id: Optional[int] = None,
        on_token: Optional[Callable[[str, int], None]] = None,
        tier: str = "standard",
        tenant: Optional[str] = None,
    ) -> str:
        """Route one request; returns its fleet-wide id."""
        if rid is None:
            self._rid_counter += 1
            rid = f"q{self._rid_counter}"
        if rid in self._records:
            raise ValueError(f"duplicate request id {rid!r}")
        prior_pin = (
            self._sessions.get(session) if session is not None else None
        )
        name = self.pick_replica(session)
        record = RouterRecord(
            rid=rid,
            prompt=np.asarray(prompt, np.int32).reshape(-1),
            max_new_tokens=int(max_new_tokens),
            eos_id=eos_id,
            replica=name,
            session=session,
            on_token=on_token,
            tier=tier,
            tenant=tenant,
        )
        # Register only after the engine ACCEPTS the request — like
        # Engine.submit, validation failures (e.g. prompt + budget
        # over max_len) must leave no phantom record behind, and the
        # session pin pick_replica just wrote must roll back too.
        try:
            self._submit_to(name, record, record.prompt,
                            record.max_new_tokens, emitted_prefix=())
        except Exception:
            if session is not None:
                if prior_pin is None:
                    self._sessions.pop(session, None)
                else:
                    self._sessions[session] = prior_pin
            raise
        self._records[rid] = record
        return rid

    def _recording_on_token(
        self, record: RouterRecord
    ) -> Callable[[str, int], None]:
        """The engine-facing token callback for one record: accumulate
        into the router's own view (failover's source of truth), relay
        to the client.  Re-created per placement — submission AND
        migration ingest — always closing over the same record, so the
        token list is continuous across replicas."""

        def recording_on_token(rid: str, tok: int) -> None:
            record.tokens.append(int(tok))
            if record.on_token is not None:
                try:
                    record.on_token(rid, tok)
                except Exception as exc:  # noqa: BLE001
                    # A broken CLIENT callback (closed socket, consumer
                    # bug) must not read as a dead REPLICA: letting it
                    # escape Engine.step would make Router.step evict
                    # the replica, resubmit elsewhere WITH the same
                    # callback, and cascade until the whole fleet is
                    # out of rotation.  Stop streaming to that client;
                    # the record keeps accumulating the tokens.
                    record.on_token = None
                    self._record_event(
                        "callback_error",
                        detail=f"{rid}: {exc!r} — streaming stopped",
                        rid=rid,
                    )

        return recording_on_token

    def _submit_to(
        self,
        name: str,
        record: RouterRecord,
        prompt: np.ndarray,
        max_new_tokens: int,
        emitted_prefix: Sequence[int],
    ) -> None:
        record.replica = name
        self.replicas[name].engine.submit(
            prompt, max_new_tokens,
            rid=record.rid, eos_id=record.eos_id,
            on_token=self._recording_on_token(record),
            emitted_prefix=list(emitted_prefix),
            tier=record.tier, tenant=record.tenant,
        )
        self._c_routed.inc(replica=name)
        self._record_event(
            "route", detail=f"{record.rid}->{name}", rid=record.rid
        )

    def result(self, rid: str) -> np.ndarray:
        """Every token ``rid`` has produced, across any failovers."""
        return np.asarray(self._records[rid].tokens, np.int32)

    def status(self, rid: str) -> str:
        record = self._records[rid]
        eng = self.replicas[record.replica].engine
        if rid in eng._requests:
            return eng.status(rid)
        return "finished" if record.done else "queued"

    def cancel(self, rid: str) -> bool:
        record = self._records.get(rid)
        if record is None:
            return False
        return self.replicas[record.replica].engine.cancel(rid)

    # ------------------------------------------------------------------ #
    # the loop                                                           #
    # ------------------------------------------------------------------ #

    @property
    def idle(self) -> bool:
        return all(
            rep.engine.scheduler.idle
            and not rep.engine.migration_pending
            for rep in self.replicas.values()
            if rep.alive
        )

    def step(self) -> bool:
        """One iteration of every in-rotation replica (a dead replica's
        failover happens inline).  Returns False when nothing ran."""
        did = False
        for index, rep in enumerate(self.replicas.values()):
            if not rep.in_rotation:
                continue
            try:
                if faults.should_die(
                    index, self._replica_steps[rep.name]
                ):
                    raise ReplicaDied(rep.name, "fault injection")
                # The serving latency fault (slow_replica_at): sleep
                # BEFORE the engine step so every token this replica
                # emits is wall-clock late — the deterministic
                # straggler the SLO burn-rate gate drives.  Host-side
                # only; never touches a traced value.
                delay = faults.replica_delay_s(index)
                # The rollout regression fault (bad_version_at): extra
                # latency WHILE this replica runs the bad param version
                # — activates the moment swap_params lands it, clears
                # the moment a rollback swaps it away.
                delay += faults.bad_version_delay_s(
                    index, int(getattr(rep.engine, "version", 0))
                )
                if delay > 0.0:
                    time.sleep(delay)
                if rep.engine._preempted():
                    # The replica's own drain request (SIGTERM via its
                    # PreemptionHandler, or request_drain()) — honored
                    # here because the router drives step(), never the
                    # engine's run() loop that normally checks this.
                    self.drain_replica(rep.name)
                    did = True
                    continue
                ran = rep.engine.step()
                if ran:
                    self._replica_steps[rep.name] += 1
                if self.disaggregated and rep.engine.role == "prefill":
                    # Hand freshly completed prompts to the decode pool
                    # right after this replica's step — a prompt never
                    # waits a full router round parked.
                    ran = self._drive_migrations(rep) or ran
                did = ran or did
            except Exception as death:  # noqa: BLE001 — any engine
                # error that escapes the engine's own transient-retry
                # guard means this replica is broken: evict it and
                # keep the fleet serving (the documented "real crash
                # surfaced by its engine step" contract).
                self.failover(rep.name, death)
                did = True
        self._slo_tick()
        return did

    def _drive_migrations(self, rep: Replica) -> bool:
        """Migrate every request ``rep`` (a prefill replica) has parked
        at prompt completion to the decode pool.  A request whose
        target pool is momentarily full — or whose session-pinned
        decode replica has no slot yet — re-parks and retries next
        step; a decode replica that FAILS mid-ingest is failed over
        (its own failover path) and the request re-parks, donor slot
        intact.  Returns True when at least one handoff completed."""
        eng = rep.engine
        if not eng.migration_pending:
            return False
        moved = False
        parked: List[Request] = []
        for req in eng.take_migration_ready():
            record = self._records.get(req.rid)
            session = record.session if record is not None else None
            try:
                target = self._decode_target(session)
            except ReplicaDied:
                # No decode replica in rotation: stay parked — the
                # pool coming back (readmit / scale-up) picks these up.
                self._record_event(
                    "migrate_wait",
                    detail=f"{req.rid}: no decode replica in rotation",
                    rid=req.rid,
                )
                parked.append(req)
                continue
            if target is None:          # decode pool full right now
                parked.append(req)
                continue
            try:
                _migration.migrate(
                    eng, self.replicas[target].engine, req,
                    on_token=(
                        self._recording_on_token(record)
                        if record is not None else None
                    ),
                )
            except Exception as death:  # noqa: BLE001 — the TARGET
                # broke mid-ingest (the donor slot is untouched: the
                # handoff frees it only after ingest succeeds).  Evict
                # the decode replica and re-park the request.
                self.failover(target, death)
                parked.append(req)
                continue
            if record is not None:
                record.replica = target
            self._c_migrations.inc()
            self._record_event(
                "kv_migrate",
                detail=(
                    f"{req.rid}: {rep.name}->{target} "
                    f"rows={req.prompt_len}"
                ),
                rid=req.rid,
            )
            moved = True
        eng._migration_ready.extend(parked)
        return moved

    def reset_replica_steps(self) -> None:
        """Re-zero the per-replica step clocks ``die_at_step`` keys on
        — e.g. between an untimed warmup pass and a timed fault region
        (``benchmarks/fleet_trace.py``), so a death step means "step
        within THIS region" rather than "since router construction"."""
        for name in self._replica_steps:
            self._replica_steps[name] = 0

    def run(self, max_steps: Optional[int] = None) -> str:
        """Step until idle or ``max_steps``; returns ``'idle'`` |
        ``'budget'``."""
        steps = 0
        while not self.idle:
            if not self.step():
                break
            steps += 1
            if max_steps is not None and steps >= max_steps:
                return "budget"
        return "idle"

    # ------------------------------------------------------------------ #
    # failover / drain                                                   #
    # ------------------------------------------------------------------ #

    def _drain_hook_for(self, name: str) -> Callable[[Dict[str, Any]], None]:
        """The :attr:`Engine.drain_hooks` callback the router registers
        on every replica: an ENGINE-initiated drain (e.g. the replica's
        preemption handler firing on SIGTERM) takes the replica out of
        rotation and resumes its in-flight requests on the survivors —
        without this, a self-draining replica would strand them.
        Router-initiated drains (failover / drain_replica) are guarded
        out: those paths consume the snapshot themselves."""

        def hook(snapshot: Dict[str, Any]) -> None:
            if name in self._router_drains:
                return
            self.replicas[name].draining = True
            kwargs = [
                kw for kw in Engine.restore_requests(snapshot)
                if kw["rid"] in self._records
            ]
            self._record_event(
                "drain",
                detail=f"{name} (engine-initiated): "
                       f"{len(kwargs)} in-flight",
            )
            try:
                self._resubmit(kwargs)
            except ReplicaDied:
                # No survivor in rotation: the snapshot is still
                # persisted by the engine's own CheckpointManager (when
                # wired) — don't break the drain's snapshot contract.
                self._record_event(
                    "drain", detail=f"{name}: no survivor to resume on"
                )

        return hook

    def _router_snapshot(self, names: Sequence[str]) -> Dict[str, Any]:
        """A drain-schema snapshot rebuilt from the router's own
        records — what failover falls back to when the dead replica
        cannot execute :meth:`Engine.drain` (hard crash).  Identical
        schema, so the SAME ``Engine.restore_requests`` parses both."""
        tree: Dict[str, Dict[str, np.ndarray]] = {}
        meta: Dict[str, Dict[str, Any]] = {}
        for rid in names:
            r = self._records[rid]
            tree[rid] = {
                "prompt": np.asarray(r.prompt, np.int32),
                "generated": np.asarray(r.tokens, np.int32),
            }
            meta[rid] = {
                "max_new_tokens": r.max_new_tokens,
                "eos_id": r.eos_id,
                "emitted_prefix": [],
                "prompt_len": int(r.prompt.size),
                "generated_len": len(r.tokens),
                "tier": r.tier,
                "tenant": r.tenant,
            }
        return {"tree": tree, "requests": meta}

    def _unfinished_on(self, name: str) -> List[str]:
        eng = self.replicas[name].engine
        return [
            r.rid
            for r in (*eng.scheduler.queue,
                      *eng.scheduler.active.values(),
                      # migration-parked work (prefill role; absent on
                      # policy-test engine facades)
                      *getattr(eng, "_migration_ready", ()))
        ]

    def _resubmit(self, kwargs: List[Dict[str, Any]]) -> None:
        for kw in kwargs:
            rid = kw["rid"]
            record = self._records[rid]
            # Drop only a STALE pin (one naming a replica out of
            # rotation): the first moved request of a session then
            # re-pins, and the session's remaining requests follow it —
            # a failover must not scatter one session across survivors.
            if record.session is not None:
                pinned = self.replicas.get(
                    self._sessions.get(record.session, "")
                )
                if pinned is None or not pinned.in_rotation:
                    self._sessions.pop(record.session, None)
            # The snapshot carries the EFFECTIVE tier (an over-budget
            # demotion mutated on the scheduler's Request): fold it
            # back into the record so the resubmission — and any later
            # failover — keeps the class the request actually ran at.
            record.tier = kw.get("tier", record.tier)
            source = record.replica
            # EVERY resumption re-prefills (the snapshot teacher-forces
            # prompt + emitted tokens), so in a disaggregated fleet the
            # target is always the PREFILL pool — decode replicas never
            # run prefill programs.  A resumed stream then re-migrates
            # to a decode survivor at prompt completion, which is where
            # "decode in-flight resumes on decode survivors" lands.
            target = self.pick_replica(
                record.session,
                role="prefill" if self.disaggregated else None,
            )
            self._submit_to(
                target, record, kw["prompt"], kw["max_new_tokens"],
                emitted_prefix=kw["emitted_prefix"],
            )
            record.moves += 1
            self._c_moved.inc()
            self._record_event(
                "req_move", detail=f"{source}->{target}", rid=rid
            )

    def failover(self, name: str,
                 error: Optional[BaseException] = None) -> List[str]:
        """Take ``name`` out of rotation and resume its in-flight
        requests elsewhere.  Prefers the engine's own cooperative drain
        (which also persists through its CheckpointManager, when wired);
        a replica too dead to drain falls back to the router-side
        snapshot.  Returns the moved rids."""
        rep = self.replicas[name]
        rep.alive = False
        self._c_failovers.inc()
        pending = self._unfinished_on(name)
        self._record_event(
            "failover",
            detail=f"{name}: {len(pending)} in-flight "
                   f"({error or 'requested'})",
        )
        snapshot: Optional[Dict[str, Any]] = None
        self._router_drains.add(name)
        try:
            snapshot = rep.engine.drain()
        except Exception:  # noqa: BLE001 — replica too dead to drain
            snapshot = None
        finally:
            self._router_drains.discard(name)
        if snapshot is None or set(snapshot["requests"]) != set(pending):
            snapshot = self._router_snapshot(pending)
        kwargs = Engine.restore_requests(snapshot)
        try:
            self._resubmit(kwargs)
        except ReplicaDied:
            # No survivor in rotation (e.g. a single-replica fleet, or
            # the last one died).  Nothing is lost: every request stays
            # in the router's records with its emitted tokens, so
            # `_router_snapshot` can rebuild them on demand — don't let
            # a second ReplicaDied escape the failover and crash run().
            self._record_event(
                "failover",
                detail=f"{name}: no survivor to resume on "
                       f"({len(kwargs)} request(s) stay recorded)",
            )
            kwargs = []
        if self.recorder is not None and hasattr(self.recorder, "dump"):
            try:
                self.recorder.dump()
            except Exception:  # noqa: BLE001 — never mask the failover
                pass
        return [kw["rid"] for kw in kwargs]

    def drain_replica(self, name: str) -> List[str]:
        """Graceful scale-down: stop routing to ``name``, drain it
        cooperatively (its CheckpointManager hook fires as usual), and
        resume its in-flight requests on the survivors."""
        rep = self.replicas[name]
        rep.draining = True
        pending = self._unfinished_on(name)
        self._router_drains.add(name)
        try:
            snapshot = rep.engine.drain()
        finally:
            self._router_drains.discard(name)
        self._record_event(
            "drain", detail=f"{name}: {len(pending)} moved"
        )
        kwargs = Engine.restore_requests(snapshot)
        self._resubmit(kwargs)
        return [kw["rid"] for kw in kwargs]

    # ------------------------------------------------------------------ #
    # SLO observe -> act                                                 #
    # ------------------------------------------------------------------ #

    def degrade(self, name: str, reason: str = "slo breach") -> List[str]:
        """Take a BREACHING replica out of rotation without killing it:
        mark it degraded, drain it cooperatively, and resume its
        in-flight requests on the survivors (the exact failover path —
        greedy streams stay bitwise).  Recorded on the registry
        (``fleet_slo_evictions``/``fleet_degraded``) and the flight
        recorder (``slo_evict``); :meth:`readmit` is the inverse."""
        rep = self.replicas[name]
        if rep.degraded:
            return []
        rep.degraded = True
        self._degraded_at[name] = self._clock()
        self._c_slo_evicted.inc(replica=name)
        self._g_degraded.set(1.0, replica=name)
        pending = self._unfinished_on(name)
        self._record_event(
            "slo_evict",
            detail=f"{name}: {reason} ({len(pending)} in-flight moved)",
        )
        self._router_drains.add(name)
        try:
            snapshot = rep.engine.drain()
        except Exception:  # noqa: BLE001 — a replica too broken to
            snapshot = None  # drain falls back to the router's records
        finally:
            self._router_drains.discard(name)
        if snapshot is None or set(snapshot["requests"]) != set(pending):
            snapshot = self._router_snapshot(pending)
        kwargs = Engine.restore_requests(snapshot)
        try:
            self._resubmit(kwargs)
        except ReplicaDied:
            # No survivor (the min-in-rotation brake should prevent
            # this, but a concurrent death can race it): the requests
            # stay recorded, same contract as failover.
            self._record_event(
                "slo_evict",
                detail=f"{name}: no survivor to resume on",
            )
            kwargs = []
        return [kw["rid"] for kw in kwargs]

    def readmit(self, name: str) -> None:
        """Return a recovered degraded replica to rotation: its windows
        came back clean, so it may serve again (its compiled programs
        and pool are intact — :meth:`Engine.resume_serving` just
        re-opens admissions)."""
        rep = self.replicas[name]
        if not rep.degraded:
            return
        rep.degraded = False
        self._degraded_at.pop(name, None)
        rep.engine.resume_serving()
        self._c_slo_readmitted.inc(replica=name)
        self._g_degraded.set(0.0, replica=name)
        self._record_event("slo_readmit", detail=name)

    def _slo_tick(self) -> None:
        """One SLO evaluation + act pass (end of every :meth:`step`).
        Breaching replicas degrade (never below ``slo_min_in_rotation``
        healthy ones); degraded replicas whose alerts cleared re-admit
        after the cooldown."""
        if self.slo is None:
            return
        self.slo.tick()
        # Only replica-split objectives may drive eviction: a tenant-
        # split breach whose tenant id collides with a replica name
        # must not read as that replica's verdict.  In a disaggregated
        # fleet the verdict is additionally PHASE-SCOPED: a replica is
        # blamed only by objectives declared for its own pool (TTFT →
        # prefill, TPOT → decode; phase-less objectives blame anyone),
        # so a prefill burst inflating TTFT can never evict a healthy
        # decode replica.
        if self.disaggregated:
            breaching = set()
            for role, names in self.pools.items():
                breaching |= (
                    set(self.slo.breaching(split_by="replica",
                                           phase=role))
                    & set(names)
                )
        else:
            breaching = self.slo.breaching(split_by="replica")
        now = self._clock()
        for name, rep in self.replicas.items():
            if rep.degraded and rep.alive and name not in breaching:
                since = now - self._degraded_at.get(name, now)
                if since >= self.slo_cooldown_s:
                    self.readmit(name)
            elif rep.in_rotation and name in breaching:
                # The min-in-rotation brake counts the breacher's OWN
                # pool: evicting the last prefill replica (or the last
                # decode one) stops the whole fleet just as surely as
                # evicting the last unified replica.
                in_rotation = sum(
                    1 for r in self.replicas.values()
                    if r.in_rotation
                    and self.roles[r.name] == self.roles[name]
                )
                if in_rotation <= self.slo_min_in_rotation:
                    self._record_event(
                        "slo_evict_skipped",
                        detail=f"{name}: breaching but only "
                               f"{in_rotation} replica(s) in rotation",
                    )
                    continue
                self.degrade(name)


__all__ = ["Replica", "ReplicaDied", "Router", "RouterRecord"]
