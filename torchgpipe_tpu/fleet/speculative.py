"""Speculative decoding over the pipelined decode path, slot-pooled.

Decode is memory-bound: every emitted token pays a full forward pass
whose matmuls are starved at batch-of-one-token per slot.  Speculative
decoding (Leviathan et al., arXiv:2211.17192) converts that into
chunked verification: a cheap DRAFT model proposes ``gamma`` tokens per
slot, and the TARGET model scores all of them in ONE chunked
``decode_slots`` step — the same slot-masked body serving already
compiles.  Greedy acceptance keeps the leading run of proposals the
target agrees with plus the target's own next token, so the output
stream is token-for-token what target-only greedy decode emits
(the batch-level theorem is already pinned by
``tests/test_speculative.py``; this module is the SERVING instance over
the slot pool).

The steady-state program-count contract survives untouched, which is
the whole design:

* the VERIFY pass reuses the engine's existing ``g > 1`` prefill
  program — that program already returns the per-position greedy grid
  (``[S, g]`` argmax), so acceptance is host-side bookkeeping over an
  output the engine fetches anyway.  ZERO new target programs.
* the draft side compiles one chunk program per prefill bucket (prompt
  mirroring AND post-acceptance catch-up share them — the catch-up lag
  is provably ≤ 2 after the first round) plus the ``g = 1`` proposal
  program.  Fixed count, independent of churn or acceptance history —
  certified statically by
  :func:`torchgpipe_tpu.analysis.serving.certify_speculative` (the
  same exhaustive-walk shape as ``certify_ladder``).

Rollback is free by construction: rejected draft tokens' KV rows sit
ABOVE the rolled-back frontier, where slot masking already makes them
dead (the property ``test_chunk_rollback_then_overwrite_is_clean``
pins).  The engine pays one ``[num_slots]`` lengths re-upload per
round — the host owns per-row acceptance, so the device frontier vector
is re-fed from the host mirror instead of the compiled step's uniform
advance.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchgpipe_tpu.models.generation import (
    _check_decodable,
    _split_params,
    decode_slots,
)
from torchgpipe_tpu.models.transformer import TransformerConfig
from torchgpipe_tpu.serving.cache_pool import CachePool
from torchgpipe_tpu.serving.engine import Engine

Pytree = Any


class SpeculativeEngine(Engine):
    """A serving :class:`Engine` whose decode phase drafts-and-verifies.

    Example::

        eng = SpeculativeEngine(
            cfg, flat_params, draft_cfg, draft_flat,
            gamma=3, num_slots=4, max_len=64, prefill_chunk=8,
        )
        rid = eng.submit(prompt, max_new_tokens=32)
        eng.run()                    # greedy == a plain Engine's output

    ``gamma`` proposals per round need a verify chunk of ``gamma + 1``
    tokens, so ``gamma + 1`` must fit the largest prefill bucket (the
    verify pass reuses that program).  Greedy only: the acceptance rule
    is argmax agreement (``temperature > 0`` is refused didactically —
    the distribution-preserving sampled variant lives at the batch
    level in ``models.generation.speculative_generate``).
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        params: Sequence[Pytree],
        draft_cfg: TransformerConfig,
        draft_params: Sequence[Pytree],
        *,
        gamma: int = 3,
        **engine_kwargs: Any,
    ) -> None:
        if gamma < 1:
            raise ValueError(f"gamma must be >= 1, got {gamma}")
        if float(engine_kwargs.get("temperature", 0.0)) != 0.0:
            raise ValueError(
                "SpeculativeEngine is greedy-only: acceptance compares "
                "argmax tokens, which preserves the target distribution "
                "only at temperature=0 — use the plain Engine (or "
                "models.generation.speculative_generate, which "
                "implements the sampled acceptance rule) for sampling"
            )
        if engine_kwargs.get("role", "unified") != "unified":
            raise ValueError(
                "SpeculativeEngine is unified-only: a speculative round "
                "interleaves draft decode with a verify pass through "
                "the prefill program, so neither phase-role's reduced "
                "program set can host it — disaggregate at the fleet "
                "level with plain prefill/decode engines instead"
            )
        if engine_kwargs.get("prefix_cache") is not None:
            raise ValueError(
                "prefix_cache + speculative decoding in ONE engine is "
                "unsupported: prefix reuse copies TARGET KV rows only, "
                "leaving the draft cache cold (an unbounded catch-up "
                "lag) — compose at the fleet level instead (router over "
                "a prefix-cached replica and a speculative replica)"
            )
        self.gamma = int(gamma)
        self.draft_cfg = draft_cfg
        self.draft_params = list(draft_params)
        _split_params(draft_cfg, self.draft_params)
        super().__init__(cfg, params, **engine_kwargs)
        if self.gamma + 1 > self.prefill_buckets[-1]:
            raise ValueError(
                f"gamma={self.gamma} needs a verify chunk of "
                f"{self.gamma + 1} tokens, but the largest prefill "
                f"bucket is {self.prefill_buckets[-1]} — the verify "
                "pass reuses the prefill program, so raise "
                "prefill_chunk or lower gamma"
            )
        _check_decodable(draft_cfg, self.pool.max_len)
        self.draft_pool = CachePool(
            draft_cfg, self.pool.num_slots, self.pool.max_len
        )
        # Device-resident draft frontier, the draft twin of the base
        # engine's _lengths_for_step/_commit_lengths: consecutive draft
        # dispatches re-feed the compiled step's own advanced lengths
        # array instead of re-uploading the host mirror; only the
        # per-round rollback (and slot recycling) invalidates it.
        self._draft_lengths_dev: Optional[Any] = None
        # Draft bucket set: the prefill ladder (prompt mirroring) plus
        # g=1 (the proposal step); catch-up lags are <= 2 and always
        # map into this set (certify_speculative walks it).
        self.draft_buckets: Tuple[int, ...] = tuple(
            sorted(set(self.prefill_buckets) | {1})
        )
        self._verify_bucket = self.scheduler.bucket_for(self.gamma + 1)
        self._build_draft_programs()
        reg = self.metrics.registry
        self._c_rounds = reg.counter(
            "serving_spec_rounds", help="speculative verify rounds")
        self._c_proposed = reg.counter(
            "serving_spec_proposed", help="draft tokens proposed")
        self._c_accepted = reg.counter(
            "serving_spec_accepted", help="draft tokens accepted")

    # ------------------------------------------------------------------ #
    # draft programs                                                     #
    # ------------------------------------------------------------------ #

    def _build_draft_programs(self) -> None:
        dcfg = self.draft_cfg
        counts = self.trace_counts

        def draft_body_for(g: int, name: str) -> Callable[..., Tuple]:
            def draft_body(params, cache, lengths, tokens, n_valid):
                counts[name] += 1
                logits, cache, new_lengths = decode_slots(
                    dcfg, params, tokens, cache, lengths, n_valid
                )
                last = jnp.clip(n_valid - 1, 0, g - 1)
                row_logits = jnp.take_along_axis(
                    logits, last[:, None, None], axis=1
                )[:, 0]
                tok = jnp.argmax(row_logits, axis=-1).astype(jnp.int32)
                return tok, cache, new_lengths
            return draft_body

        self._draft_names = {g: f"draft@{g}" for g in self.draft_buckets}
        for name in self._draft_names.values():
            counts[name] = 0
        donate = (1,) if self.donate else ()
        self._draft_fns: Dict[str, Any] = {
            name: jax.jit(draft_body_for(g, name), donate_argnums=donate)
            for g, name in self._draft_names.items()
        }
        self._draft_shapes = {
            name: (self.pool.num_slots, g)
            for g, name in self._draft_names.items()
        }

    @property
    def program_count(self) -> int:
        """Target programs (the base engine's bound, verify included at
        zero extra) plus the fixed draft set — independent of churn and
        of acceptance history."""
        return super().program_count + len(self.draft_buckets)

    def step_input_specs(self) -> Dict[str, Any]:
        specs = super().step_input_specs()
        S = self.pool.num_slots
        sds = jax.ShapeDtypeStruct
        draft_cache_spec = jax.tree_util.tree_map(
            lambda a: sds(a.shape, a.dtype), self.draft_pool.cache
        )
        for name, shape in self._draft_shapes.items():
            specs[name] = {
                "cache": draft_cache_spec,
                "lengths": sds((S,), np.int32),
                "n_valid": sds((S,), np.int32),
                "tokens": sds(shape, np.int32),
            }
        return specs

    @property
    def acceptance_rate(self) -> float:
        proposed = self._c_proposed.value()
        return self._c_accepted.value() / proposed if proposed else 0.0

    # ------------------------------------------------------------------ #
    # dispatch helpers                                                   #
    # ------------------------------------------------------------------ #

    def _dispatch_draft(
        self, g: int, tokens: np.ndarray, n_valid: np.ndarray
    ) -> np.ndarray:
        """One draft step at bucket ``g``; adopts the draft cache AND
        the advanced device frontier, mirrors the advance on the host.
        Returns the per-slot argmax tokens (host)."""
        name = self._draft_names[g]
        lengths = (
            self._draft_lengths_dev
            if self._draft_lengths_dev is not None
            else self.draft_pool.lengths_device()
        )
        tok, cache, new_lengths = self._dispatch(
            self._draft_fns[name], self.draft_params,
            self.draft_pool.cache, lengths,
            jnp.asarray(tokens), jnp.asarray(n_valid),
        )
        self.draft_pool.cache = cache
        self.draft_pool.lengths += n_valid
        self._draft_lengths_dev = new_lengths
        return np.asarray(tok)

    def _on_admit(self, req: Any) -> None:
        """A recycled slot's draft frontier resets with its target one
        (the scheduler only manages the target pool's free list; stale
        draft rows are dead by masking once the frontier is zeroed)."""
        super()._on_admit(req)
        self.draft_pool.lengths[req.slot] = 0
        self._draft_lengths_dev = None      # host mirror is authoritative

    def _after_prefill_dispatch(
        self, g: int, tokens: np.ndarray, n_valid: np.ndarray
    ) -> None:
        """Mirror the prompt chunk into the draft cache (same bucket,
        same token buffer) — draft frontiers track target frontiers
        through prefill, keeping the steady-state catch-up lag <= 2."""
        self._dispatch_draft(g, tokens, n_valid)

    # ------------------------------------------------------------------ #
    # the speculative decode round                                       #
    # ------------------------------------------------------------------ #

    @staticmethod
    def _stream_window(r: Any, start: int, n: int) -> np.ndarray:
        """Tokens ``[start, start + n)`` of the request's conceptual
        prompt+generated stream, without materializing the whole
        concatenation."""
        prompt = np.asarray(r.prompt, np.int32)
        parts: List[np.ndarray] = []
        if start < prompt.size:
            parts.append(prompt[start:start + n])
            n -= parts[-1].size
            start = 0
        else:
            start -= prompt.size
        if n > 0:
            parts.append(np.asarray(
                r.generated[start:start + n], np.int32
            ))
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    def _run_decode(self) -> None:
        reqs = self.scheduler.decode_ready()
        S = self.pool.num_slots
        gamma = self.gamma
        t_round = self._rec_clock()

        # Phase A1 — draft catch-up: feed each row the accepted tokens
        # the draft has not consumed yet, INCLUDING the current last
        # emitted token; the chunk's last-position argmax is proposal 1.
        # Only the [d_len, d_len + lag) window of the prompt+generated
        # stream is needed (lag <= 2 in steady state, <= gamma + 1
        # always) — slicing it directly keeps this hot path O(gamma)
        # per request instead of re-concatenating the whole stream
        # (O(prompt + generated), quadratic over a request's lifetime).
        lags = np.zeros((S,), np.int32)
        for r in reqs:
            t_len = int(self.pool.lengths[r.slot])
            d_len = int(self.draft_pool.lengths[r.slot])
            lags[r.slot] = t_len + 1 - d_len
        g_c = self.scheduler.bucket_for(int(lags.max()))
        cu_tokens = np.zeros((S, g_c), np.int32)
        cu_valid = np.zeros((S,), np.int32)
        for r in reqs:
            s = r.slot
            lag = int(lags[s])
            d_len = int(self.draft_pool.lengths[s])
            cu_tokens[s, :lag] = self._stream_window(r, d_len, lag)
            cu_valid[s] = lag
        proposals = np.zeros((S, gamma), np.int32)
        proposals[:, 0] = self._dispatch_draft(g_c, cu_tokens, cu_valid)

        # Phase A2 — remaining proposals, one g=1 draft step each.
        one_valid = np.zeros((S,), np.int32)
        for r in reqs:
            one_valid[r.slot] = 1
        for k in range(1, gamma):
            proposals[:, k] = self._dispatch_draft(
                1, proposals[:, k - 1:k].copy(), one_valid
            )

        # Phase B — ONE chunked target step over [cur_tok, proposals]
        # through the EXISTING prefill program at the covering bucket;
        # its per-position argmax grid is the acceptance oracle.
        g_v = self._verify_bucket
        name = self._prefill_names[g_v]
        v_tokens = self._token_buffer(name)
        v_valid = np.zeros((S,), np.int32)
        for r in reqs:
            s = r.slot
            v_tokens[s, 0] = self._cur_tok[s]
            v_tokens[s, 1:gamma + 1] = proposals[s]
            v_valid[s] = gamma + 1
        _tok, grid, cache, _lengths_dev, key = self._dispatch(
            self._prefill_fns[name], self.params, self.pool.cache,
            self._lengths_for_step(), jnp.asarray(v_tokens),
            jnp.asarray(v_valid), self._key,
        )
        self.pool.cache = cache
        self._key = key
        grid_host = np.asarray(grid)
        # The compiled step advanced every row's device frontier by
        # gamma+1; acceptance is PER-ROW, so the host mirror is
        # authoritative and the device vector re-uploads next step.
        self._lengths_dev = None
        self._lengths_shadow = None
        self.metrics.step("decode", len(reqs), S)
        self._c_rounds.inc()
        self._c_proposed.inc(gamma * len(reqs))

        # Phase C — greedy acceptance + rollback, all host-side.  The
        # per-row rollback makes the host mirror authoritative for BOTH
        # pools: the draft device frontier re-uploads at the next
        # round's catch-up (its one per-round host→device copy).
        self._draft_lengths_dev = None
        for r in reqs:
            s = r.slot
            target = grid_host[s, :gamma + 1]
            n = 0
            while n < gamma and proposals[s, n] == target[n]:
                n += 1
            emitted = [int(t) for t in proposals[s, :n]] + [int(target[n])]
            self._c_accepted.inc(n)
            # One rid-keyed span per speculative round: draft catch-up
            # + proposals + the chunked verify, with the acceptance
            # count — the request-trace twin of the round counters.
            if self.recorder is not None:
                self._rec(
                    "req_spec_round", r.rid,
                    dur=max(self._rec_clock() - t_round, 0.0),
                    detail=f"proposed={gamma} accepted={n}",
                )
            # Frontiers BEFORE emission (emission may free the slot):
            # target keeps [.., cur_tok, d1..dn]; rejected rows above
            # the frontier are dead by masking.  The draft consumed
            # d1..d_{gamma-1} — its valid run is d1..dn capped there.
            t_len = int(self.pool.lengths[s])
            self.pool.lengths[s] = t_len + 1 + n
            self.draft_pool.lengths[s] = t_len + 1 + min(n, gamma - 1)
            for tok in emitted:
                if r.status != "active":
                    break       # budget/eos hit mid-round: drop the rest
                self._emit(r, tok)


__all__ = ["SpeculativeEngine"]
