"""Live train→serve weight rollout: version the fleet, never drop it.

Continuous learning closes the loop the repo has built toward: the
training stack emits improved params every few megasteps, and the
serving fleet should pick them up WITHOUT a restart, a recompile, or a
dropped request.  Two facts make that cheap here:

* **Params are a call argument, not a constant.**  Every compiled
  serving program takes the weights as a traced ARGUMENT
  (``Engine._dispatch(fn, self.params, ...)``), so replacing
  ``self.params`` with a same-shape/same-dtype pytree changes ZERO
  compiled programs — :meth:`Engine.swap_params` is a pointer swap plus
  a version bump.  :func:`torchgpipe_tpu.analysis.serving.certify_swap`
  certifies the shape/dtype signature statically at publish time; a
  re-shaped model is REFUSED (it would recompile every program
  mid-serve) and must cold-start a fresh engine instead.
* **The drain path already moves requests without losing tokens.**
  :meth:`Router.drain_replica` parks a replica and resumes its
  in-flight requests on the survivors, teacher-forced to their last
  emitted token.  A rolling update is that path with a swap in the
  middle: drain → ``swap_params`` → readmit, one replica per tick —
  the fleet serves version N and N+1 CONCURRENTLY mid-rollout and
  every request finishes somewhere.

:class:`RolloutController` adds the policy, shaped like the
:class:`~torchgpipe_tpu.fleet.autoscaler.Autoscaler` (observe →
at-most-one-action-per-tick):

* :meth:`publish` registers a new param version — monotonic version
  numbers, ``certify_swap``-gated (an incompatible publish raises and
  changes nothing).
* :meth:`tick` advances the rollout one action at a time: first the
  HEALTH GATE — an SLO burn-rate alert blaming a replica that already
  runs the new version triggers :meth:`rollback` (the fleet returns to
  the last-good version, again one swap per tick) — then at most one
  drain→swap→readmit.
* The baseline advances only when EVERY alive replica serves the
  target — until then rollback is one flag flip away, which is the
  whole point of keeping version N's params around.

Every swap and rollback lands on the registry
(``rollout_version{replica=...}``, ``rollout_target_version``,
``rollout_swaps_total``, ``rollout_rollbacks_total``) and the flight
recorder (``rollout`` events); each request's ``req_submit`` /
``req_finish`` trace spans carry ``version=`` from the engine that
served them, so a stitched trace shows exactly which responses came
from which weights.  ``tools/rollout_verify.py`` gates the killer
property: a swapped engine's streams are BITWISE a cold-started
engine's on the published params, and an induced bad version
(``faults.inject(bad_version_at=...)``) rolls back automatically with
zero dropped requests.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from torchgpipe_tpu.fleet.router import Router
from torchgpipe_tpu.serving.engine import Engine


def publish(controller: "RolloutController", params: Any,
            version: int) -> int:
    """Module-level convenience: the train loop's one-liner
    (``rollout.publish(ctl, params, v)``) — see
    :meth:`RolloutController.publish`."""
    return controller.publish(params, version)


class RolloutController:
    """Rolling weight updates over a :class:`Router`'s fleet.

    Drive it like the autoscaler: :meth:`publish` when training emits
    a candidate, :meth:`tick` once per router step.  ``tick`` returns
    the action it took (``"swap:<replica>:v<version>"`` /
    ``"rollback:v<version>"`` / ``"complete:v<version>"``) or ``None``.

    ``slo`` defaults to the router's own monitor — the same burn-rate
    verdicts that degrade a replica also veto its new weights.  The
    health gate only fires while a rollout is IN FLIGHT (target !=
    baseline) and only on replicas already at the target version, so a
    pre-existing breach elsewhere cannot mis-blame fresh weights.
    """

    def __init__(
        self,
        router: Router,
        *,
        slo: Optional[Any] = None,
        recorder: Optional[Any] = None,
    ) -> None:
        self.router = router
        self.slo = slo if slo is not None else router.slo
        self.recorder = (
            recorder if recorder is not None else router.recorder
        )
        # Version 0 (or whatever the engines booted at) is the rollback
        # floor: capture the currently-served params so `rollback` can
        # always swap BACK, not just stop swapping forward.
        first = next(iter(router.replicas.values())).engine
        self.baseline = int(getattr(first, "version", 0))
        self.target = self.baseline
        self.published: Dict[int, Any] = {self.baseline: first.params}
        registry = router.registry
        self._g_version = registry.gauge(
            "rollout_version",
            help="param version each replica currently serves",
            labels=("replica",),
        )
        self._g_target = registry.gauge(
            "rollout_target_version",
            help="param version the rollout is converging the fleet to",
        )
        self._c_swaps = registry.counter(
            "rollout_swaps_total",
            help="drain→swap_params→readmit actions performed",
            labels=("replica",),
        )
        self._c_rollbacks = registry.counter(
            "rollout_rollbacks_total",
            help="rollouts reverted to the baseline version",
        )
        for name, rep in router.replicas.items():
            self._g_version.set(
                float(getattr(rep.engine, "version", 0)), replica=name
            )
        self._g_target.set(float(self.target))

    # ------------------------------------------------------------------ #
    # publish / rollback                                                 #
    # ------------------------------------------------------------------ #

    def _record(self, detail: str) -> None:
        if self.recorder is not None:
            try:
                self.recorder.record("rollout", detail=detail)
            except Exception:  # noqa: BLE001 - telemetry is best-effort
                pass

    def publish(self, params: Any, version: int) -> int:
        """Register param ``version`` as the fleet's new target.

        Versions are monotonic (publishing at-or-below the current
        target raises — a rollback is :meth:`rollback`, not a
        re-publish), and the pytree is certified against a live
        engine's signature BEFORE anything changes: a shape/dtype
        mismatch raises ``ValueError`` with the first mismatching leaf
        named, and the fleet keeps serving exactly as before.  Returns
        the number of replicas the rollout will visit."""
        version = int(version)
        if version <= self.target:
            raise ValueError(
                f"published version {version} is not above the current "
                f"target {self.target} — versions are monotonic "
                "(use rollback() to go backward)"
            )
        from torchgpipe_tpu.analysis.diagnostics import Severity
        from torchgpipe_tpu.analysis.serving import certify_swap

        engine = next(
            rep.engine for rep in self.router.replicas.values()
            if rep.alive
        )
        findings = certify_swap(engine, params)
        errors = [f for f in findings if f.severity >= Severity.ERROR]
        if errors:
            raise ValueError(
                f"publish refused for version {version}: "
                + errors[0].message
            )
        self.published[version] = params
        self.target = version
        self._g_target.set(float(version))
        n = sum(1 for r in self.router.replicas.values() if r.alive)
        self._record(f"publish v{version}: {n} replica(s) to visit")
        return n

    def rollback(self, reason: str = "requested") -> str:
        """Revert the fleet's target to the baseline version.  The
        actual swaps happen one per :meth:`tick` through the same
        drain→swap→readmit path (a rollback IS a rollout, aimed
        backward); the bad version's params stay registered for the
        postmortem but will never be targeted again."""
        bad = self.target
        self.target = self.baseline
        self._g_target.set(float(self.target))
        self._c_rollbacks.inc()
        self._record(
            f"rollback v{bad}->v{self.baseline}: {reason}"
        )
        return f"rollback:v{self.baseline}"

    # ------------------------------------------------------------------ #
    # the control loop                                                   #
    # ------------------------------------------------------------------ #

    def _version_of(self, name: str) -> int:
        return int(getattr(self.router.replicas[name].engine,
                           "version", 0))

    def versions(self) -> Dict[str, int]:
        """Param version per alive replica — the mid-rollout witness
        that the fleet serves two versions concurrently."""
        return {
            name: self._version_of(name)
            for name, rep in self.router.replicas.items()
            if rep.alive
        }

    def _pending(self) -> List[str]:
        """Alive replicas not yet at the target version, in name order
        (deterministic visit order).  Degraded/draining replicas are
        INCLUDED: a rollback must reach the very replica the SLO layer
        evicted, or it re-burns the moment it is readmitted."""
        return sorted(
            name for name, rep in self.router.replicas.items()
            if rep.alive and self._version_of(name) != self.target
        )

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One rollout action: health-gate first, then at most one
        replica swap, then (once converged) baseline finalization."""
        del now  # signature symmetry with Autoscaler.tick
        # 1) Health gate — only while a rollout is in flight, only on
        #    replicas ALREADY at the target: their burn is the new
        #    weights' burn.  One rollback per publish; the swaps back
        #    to baseline then proceed one per tick below.
        if self.slo is not None and self.target != self.baseline:
            if self.slo is not self.router.slo:
                self.slo.tick()
            breaching = set(self.slo.breaching(split_by="replica"))
            burned = sorted(
                name for name in breaching
                if name in self.router.replicas
                and self._version_of(name) == self.target
            )
            if burned:
                return self.rollback(
                    f"slo burn on updated replica(s) "
                    f"{', '.join(burned)}"
                )
        # 2) At most one swap.
        pending = self._pending()
        if pending:
            return self._swap(pending[0])
        # 3) Converged: advance the baseline (finalize) exactly once.
        if self.target != self.baseline:
            old = self.baseline
            self.baseline = self.target
            self._record(f"complete v{old}->v{self.target}")
            return f"complete:v{self.target}"
        return None

    def _swap(self, name: str) -> str:
        """Drain → :meth:`Engine.swap_params` → readmit, for one
        replica.  The drain is the router's own (same snapshot schema,
        same checkpoint hooks); the replica re-enters rotation BEFORE
        the drained requests resubmit, so even a single-replica fleet
        rolls with zero dropped requests (the requests simply resume on
        the freshly-swapped replica itself)."""
        rep = self.router.replicas[name]
        target = self.target
        was_draining = rep.draining
        rep.draining = True
        self.router._router_drains.add(name)
        try:
            snapshot = rep.engine.drain()
        finally:
            self.router._router_drains.discard(name)
        rep.engine.swap_params(self.published[target], target)
        rep.draining = was_draining
        rep.engine.resume_serving()
        self._g_version.set(float(target), replica=name)
        self._c_swaps.inc(replica=name)
        kwargs = Engine.restore_requests(snapshot)
        if kwargs:
            self.router._resubmit(kwargs)
        self._record(
            f"swap {name} -> v{target} "
            f"({len(kwargs)} in-flight moved)"
        )
        return f"swap:{name}:v{target}"


__all__ = ["RolloutController", "publish"]
