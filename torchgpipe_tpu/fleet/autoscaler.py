"""SLO-priced fleet autoscaler: replica count as a control loop.

The :class:`~torchgpipe_tpu.fleet.router.Router` already owns every
MECHANISM an elastic fleet needs — ``drain_replica`` parks a replica
without dropping an in-flight request (drained state resumes on the
survivors; greedy streams stay bitwise), ``Engine.resume_serving``
un-parks one with its compiled programs and KV pool intact, and the SLO
layer (:class:`~torchgpipe_tpu.obs.slo.SloMonitor`) measures burn.
:class:`Autoscaler` adds the POLICY:

* **Pricing.**  Desired replica count comes from Little's law: arrival
  rate λ over a sliding window × the per-request service time, divided
  by one replica's slot capacity, padded by ``headroom``.  Service
  time is priced off the measured
  :class:`~torchgpipe_tpu.obs.costmodel.CostModel` when one is supplied
  and fresh (per-token decode cost = the summed per-stage forward
  atoms × ``tokens_per_request``), else the explicit
  ``service_time_s``.
* **SLO burn override.**  While a burn-rate alert is firing, demand
  math is moot — the fleet is under-provisioned NOW, so desired is
  bumped one above the active count regardless of λ.
* **Hysteresis + cooldown.**  A resize needs ``hold_ticks``
  CONSECUTIVE ticks agreeing on the same direction, and at most one
  resize per ``cooldown_s`` — bursty MMPP arrivals (see
  :mod:`torchgpipe_tpu.fleet.trace`) flip the instantaneous desired
  count constantly; the damping is what converts that into a calm
  replica trajectory.
* **Bounds.**  Never above the replicas the router actually has, never
  below ``max(min_replicas, router.slo_min_in_rotation)`` — the same
  brake that stops the SLO layer from degrading the last healthy
  replica stops the autoscaler from parking it.

Scale-down reuses :meth:`Router.drain_replica` verbatim (the
acceptance property "never drops an in-flight request across a
scale-down" is inherited, not re-implemented); scale-up clears the
parked replica's ``draining`` flag and re-opens admissions.  Every
decision lands on the registry (``autoscaler_desired_replicas`` /
``autoscaler_active_replicas`` gauges,
``autoscaler_resizes_total{direction}``) and the router's flight
recorder (``autoscale`` events) — the serving twin of the training
supervisor's ``supervisor_resize`` trail.  See docs/serving.md for the
policy table.
"""

from __future__ import annotations

import collections
import math
from typing import Any, Deque, List, Optional

from torchgpipe_tpu.fleet.router import Router


class Autoscaler:
    """Price replica count against measured cost + arrival rate.

    Drive it like the router's SLO loop: call :meth:`observe_arrival`
    as requests land (the trace-replay loop does this naturally) and
    :meth:`tick` once per router step.  ``tick`` returns the action it
    took (``"up:<replica>"`` / ``"down:<replica>"``) or ``None``.

    Exactly one of ``cost_model`` / ``service_time_s`` prices a
    request; with both, a FRESH cost model wins and ``service_time_s``
    is the stale fallback.
    """

    def __init__(
        self,
        router: Router,
        *,
        slo: Optional[Any] = None,
        cost_model: Optional[Any] = None,
        pipe: Optional[Any] = None,
        service_time_s: Optional[float] = None,
        tokens_per_request: int = 8,
        window_s: float = 1.0,
        headroom: float = 1.3,
        min_replicas: int = 1,
        max_replicas: Optional[int] = None,
        hold_ticks: int = 3,
        cooldown_s: float = 0.0,
        recorder: Optional[Any] = None,
    ) -> None:
        if cost_model is None and service_time_s is None:
            raise ValueError(
                "the autoscaler needs a price: pass cost_model= (measured) "
                "or service_time_s= (declared)"
            )
        if window_s <= 0:
            raise ValueError("window_s must be > 0")
        if headroom < 1.0:
            raise ValueError(
                "headroom < 1 plans to miss the SLO it protects"
            )
        if hold_ticks < 1:
            raise ValueError("hold_ticks must be >= 1")
        self.router = router
        self.slo = slo
        self.cost_model = cost_model
        self.pipe = pipe
        self.service_time_s = service_time_s
        self.tokens_per_request = int(tokens_per_request)
        self.window_s = float(window_s)
        self.headroom = float(headroom)
        n_total = len(router.replicas)
        self.min_replicas = max(
            int(min_replicas), int(router.slo_min_in_rotation)
        )
        self.max_replicas = min(
            int(max_replicas) if max_replicas is not None else n_total,
            n_total,
        )
        if self.min_replicas > self.max_replicas:
            raise ValueError(
                f"min_replicas {self.min_replicas} (after the "
                f"slo_min_in_rotation floor) exceeds max_replicas "
                f"{self.max_replicas}"
            )
        self.hold_ticks = int(hold_ticks)
        self.cooldown_s = float(cooldown_s)
        self.recorder = (
            recorder if recorder is not None else router.recorder
        )
        self.parked: List[str] = []
        self._clock = router._clock
        self._arrivals: Deque[float] = collections.deque()
        self._trend_dir = 0       # sign of the pending resize
        self._trend_ticks = 0     # consecutive ticks agreeing with it
        self._last_resize_at: Optional[float] = None
        registry = router.registry
        self._g_desired = registry.gauge(
            "autoscaler_desired_replicas",
            help="replica count the pricing asks for (pre-damping)",
        )
        self._g_active = registry.gauge(
            "autoscaler_active_replicas",
            help="replicas currently serving (not parked/degraded/dead)",
        )
        self._c_resizes = registry.counter(
            "autoscaler_resizes_total",
            help="park/unpark actions the autoscaler performed",
            labels=("direction",),
        )
        self._g_active.set(float(self._active()))

    # ------------------------------------------------------------------ #
    # measurement                                                        #
    # ------------------------------------------------------------------ #

    def observe_arrival(
        self, n: int = 1, now: Optional[float] = None
    ) -> None:
        """Record ``n`` request arrivals (at ``now``, default the
        router's clock) into the sliding rate window."""
        t = self._clock() if now is None else float(now)
        for _ in range(max(int(n), 0)):
            self._arrivals.append(t)

    def arrival_rate(self, now: Optional[float] = None) -> float:
        """Arrivals per second over the trailing ``window_s``."""
        t = self._clock() if now is None else float(now)
        cutoff = t - self.window_s
        while self._arrivals and self._arrivals[0] < cutoff:
            self._arrivals.popleft()
        return len(self._arrivals) / self.window_s

    def request_service_time_s(self) -> float:
        """Seconds of replica time one request costs — the measured
        cost model's summed per-stage forward atoms × tokens per
        request when fresh, else the declared ``service_time_s``."""
        cm = self.cost_model
        if cm is not None:
            stale = (
                cm.stale_reason(self.pipe) if self.pipe is not None
                else None
            )
            if stale is None:
                try:
                    n_stages = int(cm.fingerprint["n_stages"])
                    atoms, _exact = cm.stage_atoms(n_stages)
                except (KeyError, TypeError, ValueError):
                    atoms = None  # malformed model: declared fallback
                if atoms:
                    # One decode token flows through every stage's
                    # forward once; backward atoms are training-only.
                    per_token = sum(f for f, _, _ in atoms.values())
                    return per_token * self.tokens_per_request
        if self.service_time_s is None:
            raise ValueError(
                "cost model is stale/unusable and no service_time_s "
                "fallback was declared"
            )
        return float(self.service_time_s)

    # ------------------------------------------------------------------ #
    # policy                                                             #
    # ------------------------------------------------------------------ #

    def _active(self) -> int:
        return sum(
            1 for r in self.router.replicas.values() if r.in_rotation
        )

    def _slots_per_replica(self) -> int:
        for rep in self.router.replicas.values():
            pool = getattr(rep.engine, "pool", None)
            slots = getattr(pool, "num_slots", None)
            if slots:
                return int(slots)
        return 1

    def desired_replicas(self, now: Optional[float] = None) -> int:
        """The UNDAMPED verdict this tick: Little's-law demand, bumped
        above active while an SLO alert burns, clamped to bounds."""
        lam = self.arrival_rate(now)
        demand = lam * self.request_service_time_s() * self.headroom
        want = max(
            self.min_replicas,
            math.ceil(demand / self._slots_per_replica() - 1e-9),
        )
        if self.slo is not None and self.slo.active_alerts():
            want = max(want, self._active() + 1)
        return min(max(want, self.min_replicas), self.max_replicas)

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One policy evaluation: damp the instantaneous desired count
        through hysteresis + cooldown, then park or un-park at most ONE
        replica.  Returns the action taken or ``None``."""
        t = self._clock() if now is None else float(now)
        desired = self.desired_replicas(t)
        active = self._active()
        self._g_desired.set(float(desired))
        self._g_active.set(float(active))
        direction = (desired > active) - (desired < active)
        if direction == 0:
            self._trend_dir = 0
            self._trend_ticks = 0
            return None
        if direction == self._trend_dir:
            self._trend_ticks += 1
        else:
            self._trend_dir = direction
            self._trend_ticks = 1
        if self._trend_ticks < self.hold_ticks:
            return None
        if (
            self._last_resize_at is not None
            and t - self._last_resize_at < self.cooldown_s
        ):
            return None
        action = (
            self._scale_up() if direction > 0 else self._scale_down()
        )
        if action is not None:
            self._last_resize_at = t
            self._trend_dir = 0
            self._trend_ticks = 0
            self._g_active.set(float(self._active()))
        return action

    # ------------------------------------------------------------------ #
    # actuation                                                          #
    # ------------------------------------------------------------------ #

    def _record(self, detail: str) -> None:
        if self.recorder is not None:
            try:
                self.recorder.record("autoscale", detail=detail)
            except Exception:  # noqa: BLE001 - telemetry is best-effort
                pass

    def _scale_down(self) -> Optional[str]:
        if self._active() <= self.min_replicas:
            return None
        # Deterministic victim: the last in-rotation replica by name —
        # scale-up un-parks in the reverse order, so the fleet breathes
        # through the same replicas and their warm compiled programs.
        candidates = sorted(
            name for name, rep in self.router.replicas.items()
            if rep.in_rotation
        )
        victim = candidates[-1]
        moved = self.router.drain_replica(victim)
        self.parked.append(victim)
        self._c_resizes.inc(direction="down")
        self._record(
            f"down {victim}: {len(moved)} in-flight moved, "
            f"{self._active()} active"
        )
        return f"down:{victim}"

    def _scale_up(self) -> Optional[str]:
        if not self.parked or self._active() >= self.max_replicas:
            return None
        name = self.parked.pop()
        rep = self.router.replicas[name]
        rep.draining = False
        rep.engine.resume_serving()
        self._c_resizes.inc(direction="up")
        self._record(f"up {name}: {self._active()} active")
        return f"up:{name}"


__all__ = ["Autoscaler"]
