"""SLO-priced fleet autoscaler: replica count as a control loop.

The :class:`~torchgpipe_tpu.fleet.router.Router` already owns every
MECHANISM an elastic fleet needs — ``drain_replica`` parks a replica
without dropping an in-flight request (drained state resumes on the
survivors; greedy streams stay bitwise), ``Engine.resume_serving``
un-parks one with its compiled programs and KV pool intact, and the SLO
layer (:class:`~torchgpipe_tpu.obs.slo.SloMonitor`) measures burn.
:class:`Autoscaler` adds the POLICY:

* **Pricing.**  Desired replica count comes from Little's law: arrival
  rate λ over a sliding window × the per-request service time, divided
  by one replica's slot capacity, padded by ``headroom``.  Service
  time is priced off the measured
  :class:`~torchgpipe_tpu.obs.costmodel.CostModel` when one is supplied
  and fresh (per-token decode cost = the summed per-stage forward
  atoms × ``tokens_per_request``), else the explicit
  ``service_time_s``.
* **SLO burn override.**  While a burn-rate alert is firing, demand
  math is moot — the fleet is under-provisioned NOW, so desired is
  bumped one above the active count regardless of λ.
* **Hysteresis + cooldown.**  A resize needs ``hold_ticks``
  CONSECUTIVE ticks agreeing on the same direction, and at most one
  resize per ``cooldown_s`` — bursty MMPP arrivals (see
  :mod:`torchgpipe_tpu.fleet.trace`) flip the instantaneous desired
  count constantly; the damping is what converts that into a calm
  replica trajectory.
* **Bounds.**  Never above the replicas the router actually has, never
  below ``max(min_replicas, router.slo_min_in_rotation)`` — the same
  brake that stops the SLO layer from degrading the last healthy
  replica stops the autoscaler from parking it.
* **Per-role pools.**  On a phase-disaggregated fleet (see
  :mod:`torchgpipe_tpu.fleet.migration`) every term above goes
  per-pool: the prefill pool is priced by the ADMISSION window (TTFT
  lives there), the decode pool by the fleet's migration rate — each
  completed prompt is one decode arrival, read off the router's
  ``fleet_migrations`` counter — and SLO-burn alerts bump only the
  pool their objective's ``phase`` blames.  Floors are per-pool too:
  the decode pool is never parked below its own floor to feed
  prefill — decode replicas hold live token streams, and a starved
  decode pool turns a TTFT problem into a TPOT outage.

Scale-down reuses :meth:`Router.drain_replica` verbatim (the
acceptance property "never drops an in-flight request across a
scale-down" is inherited, not re-implemented); scale-up clears the
parked replica's ``draining`` flag and re-opens admissions.  Every
decision lands on the registry (``autoscaler_desired_replicas`` /
``autoscaler_active_replicas`` gauges,
``autoscaler_resizes_total{direction}``) and the router's flight
recorder (``autoscale`` events) — the serving twin of the training
supervisor's ``supervisor_resize`` trail.  See docs/serving.md for the
policy table.
"""

from __future__ import annotations

import collections
import math
from typing import Any, Deque, List, Mapping, Optional, Tuple

from torchgpipe_tpu.fleet.router import Router


class Autoscaler:
    """Price replica count against measured cost + arrival rate.

    Drive it like the router's SLO loop: call :meth:`observe_arrival`
    as requests land (the trace-replay loop does this naturally) and
    :meth:`tick` once per router step.  ``tick`` returns the action it
    took (``"up:<replica>"`` / ``"down:<replica>"``) or ``None``.

    Exactly one of ``cost_model`` / ``service_time_s`` prices a
    request; with both, a FRESH cost model wins and ``service_time_s``
    is the stale fallback.
    """

    def __init__(
        self,
        router: Router,
        *,
        slo: Optional[Any] = None,
        cost_model: Optional[Any] = None,
        pipe: Optional[Any] = None,
        service_time_s: Optional[float] = None,
        tokens_per_request: int = 8,
        window_s: float = 1.0,
        headroom: float = 1.3,
        min_replicas: int = 1,
        max_replicas: Optional[int] = None,
        hold_ticks: int = 3,
        cooldown_s: float = 0.0,
        recorder: Optional[Any] = None,
        tier_weights: Optional[Mapping[str, float]] = None,
    ) -> None:
        if cost_model is None and service_time_s is None:
            raise ValueError(
                "the autoscaler needs a price: pass cost_model= (measured) "
                "or service_time_s= (declared)"
            )
        if window_s <= 0:
            raise ValueError("window_s must be > 0")
        if headroom < 1.0:
            raise ValueError(
                "headroom < 1 plans to miss the SLO it protects"
            )
        if hold_ticks < 1:
            raise ValueError("hold_ticks must be >= 1")
        self.router = router
        self.slo = slo
        self.cost_model = cost_model
        self.pipe = pipe
        self.service_time_s = service_time_s
        self.tokens_per_request = int(tokens_per_request)
        self.window_s = float(window_s)
        self.headroom = float(headroom)
        n_total = len(router.replicas)
        self.min_replicas = max(
            int(min_replicas), int(router.slo_min_in_rotation)
        )
        self.max_replicas = min(
            int(max_replicas) if max_replicas is not None else n_total,
            n_total,
        )
        if self.min_replicas > self.max_replicas:
            raise ValueError(
                f"min_replicas {self.min_replicas} (after the "
                f"slo_min_in_rotation floor) exceeds max_replicas "
                f"{self.max_replicas}"
            )
        self.hold_ticks = int(hold_ticks)
        self.cooldown_s = float(cooldown_s)
        self.recorder = (
            recorder if recorder is not None else router.recorder
        )
        self.parked: List[str] = []
        self._clock = router._clock
        # QoS-tier demand pricing (serving/qos.py): each arrival enters
        # the window with its tier's weight, so an interactive-heavy
        # mix — which must hold a tighter latency SLO — prices more
        # replicas than the same λ of batch traffic.  Unweighted (every
        # tier 1.0) without a map; unknown tiers weigh 1.0.
        self.tier_weights = (
            dict(tier_weights) if tier_weights is not None else None
        )
        if self.tier_weights is not None:
            for w in self.tier_weights.values():
                if float(w) <= 0.0:
                    raise ValueError("tier weights must be > 0")
        self._arrivals: Deque[Tuple[float, float]] = collections.deque()
        # Phase-disaggregated fleets are priced per pool; a unified
        # fleet is the degenerate single-pool case of the same loop.
        self.disaggregated = bool(getattr(router, "disaggregated", False))
        self.roles = dict(getattr(router, "roles", {})) or {
            name: "unified" for name in router.replicas
        }
        self.role_order = (
            ("prefill", "decode") if self.disaggregated else ("unified",)
        )
        for role in self.role_order:
            n_pool = sum(1 for v in self.roles.values() if v == role)
            if self.disaggregated and self.min_replicas > n_pool:
                raise ValueError(
                    f"min_replicas {self.min_replicas} (after the "
                    f"slo_min_in_rotation floor) exceeds the {role} "
                    f"pool's {n_pool} replicas"
                )
        # Per-role hysteresis state: [pending direction, agreeing ticks].
        self._trend = {role: [0, 0] for role in self.role_order}
        # Decode arrivals = migration handoffs; rate is read as counter
        # deltas over the window, sampled each tick: (t, count) pairs.
        self._migrations: Deque[tuple] = collections.deque([(
            -math.inf,
            float(getattr(router, "_c_migrations", None).value())
            if getattr(router, "_c_migrations", None) is not None else 0.0,
        )])
        self._last_resize_at: Optional[float] = None
        registry = router.registry
        self._g_desired = registry.gauge(
            "autoscaler_desired_replicas",
            help="replica count the pricing asks for (pre-damping)",
        )
        self._g_active = registry.gauge(
            "autoscaler_active_replicas",
            help="replicas currently serving (not parked/degraded/dead)",
        )
        self._c_resizes = registry.counter(
            "autoscaler_resizes_total",
            help="park/unpark actions the autoscaler performed",
            labels=("direction",),
        )
        self._g_active.set(float(self._active()))

    # ------------------------------------------------------------------ #
    # measurement                                                        #
    # ------------------------------------------------------------------ #

    def observe_arrival(
        self, n: int = 1, now: Optional[float] = None,
        tier: Optional[str] = None,
    ) -> None:
        """Record ``n`` request arrivals (at ``now``, default the
        router's clock) into the sliding rate window.  With
        ``tier_weights`` configured, each arrival carries its tier's
        weight into the demand math (``tier=None`` weighs 1.0)."""
        t = self._clock() if now is None else float(now)
        w = 1.0
        if self.tier_weights is not None and tier is not None:
            w = float(self.tier_weights.get(tier, 1.0))
        for _ in range(max(int(n), 0)):
            self._arrivals.append((t, w))

    def arrival_rate(self, now: Optional[float] = None) -> float:
        """WEIGHTED arrivals per second over the trailing ``window_s``
        (plain arrivals/s when no tier weights are configured)."""
        t = self._clock() if now is None else float(now)
        cutoff = t - self.window_s
        while self._arrivals and self._arrivals[0][0] < cutoff:
            self._arrivals.popleft()
        return sum(w for _, w in self._arrivals) / self.window_s

    def migration_rate(self, now: Optional[float] = None) -> float:
        """Prefill→decode handoffs per second over the trailing
        ``window_s`` — the decode pool's OWN arrival rate, sampled as
        deltas of the router's ``fleet_migrations`` counter.  Nobody
        calls :meth:`observe_arrival` for migrations; the router's
        counter is the ground truth, so the decode pool cannot be
        mis-priced by a caller forgetting to report handoffs."""
        counter = getattr(self.router, "_c_migrations", None)
        if counter is None:
            return 0.0
        t = self._clock() if now is None else float(now)
        self._migrations.append((t, float(counter.value())))
        cutoff = t - self.window_s
        # Keep one sample at/before the cutoff as the window baseline.
        while len(self._migrations) >= 2 and self._migrations[1][0] <= cutoff:
            self._migrations.popleft()
        return max(
            0.0,
            (self._migrations[-1][1] - self._migrations[0][1])
            / self.window_s,
        )

    def request_service_time_s(self) -> float:
        """Seconds of replica time one request costs — the measured
        cost model's summed per-stage forward atoms × tokens per
        request when fresh, else the declared ``service_time_s``."""
        cm = self.cost_model
        if cm is not None:
            stale = (
                cm.stale_reason(self.pipe) if self.pipe is not None
                else None
            )
            if stale is None:
                try:
                    n_stages = int(cm.fingerprint["n_stages"])
                    atoms, _exact = cm.stage_atoms(n_stages)
                except (KeyError, TypeError, ValueError):
                    atoms = None  # malformed model: declared fallback
                if atoms:
                    # One decode token flows through every stage's
                    # forward once; backward atoms are training-only.
                    per_token = sum(f for f, _, _ in atoms.values())
                    return per_token * self.tokens_per_request
        if self.service_time_s is None:
            raise ValueError(
                "cost model is stale/unusable and no service_time_s "
                "fallback was declared"
            )
        return float(self.service_time_s)

    # ------------------------------------------------------------------ #
    # policy                                                             #
    # ------------------------------------------------------------------ #

    def _active(self, role: Optional[str] = None) -> int:
        return sum(
            1 for name, r in self.router.replicas.items()
            if r.in_rotation
            and (role is None or self.roles.get(name) == role)
        )

    def _pool_size(self, role: str) -> int:
        return sum(1 for v in self.roles.values() if v == role)

    def _slots_per_replica(self, role: Optional[str] = None) -> int:
        for name, rep in self.router.replicas.items():
            if role is not None and self.roles.get(name) != role:
                continue
            pool = getattr(rep.engine, "pool", None)
            slots = getattr(pool, "num_slots", None)
            if slots:
                return int(slots)
        return 1

    def _alert_blames(self, role: str) -> bool:
        """Whether any firing SLO alert's objective blames ``role`` —
        phase-less objectives blame every pool (and a unified fleet's
        single pool absorbs everything)."""
        alerts = self.slo.active_alerts()
        if not alerts:
            return False
        if not self.disaggregated:
            return True
        phase_of = {
            o.name: getattr(o, "phase", None)
            for o in getattr(self.slo, "objectives", ())
        }
        for alert in alerts:
            name = alert[0] if isinstance(alert, tuple) else alert
            if phase_of.get(name) in (None, role):
                return True
        return False

    def desired_replicas(
        self, now: Optional[float] = None, role: Optional[str] = None,
    ) -> int:
        """The UNDAMPED verdict this tick: Little's-law demand, bumped
        above active while an SLO alert burns, clamped to bounds.  On a
        disaggregated fleet pass ``role`` — the prefill pool is priced
        by the admission window, the decode pool by the migration rate
        (omitting it sums both pools' verdicts, the fleet total)."""
        if role is None and self.disaggregated:
            return sum(
                self.desired_replicas(now, r) for r in self.role_order
            )
        lam = (
            self.migration_rate(now) if role == "decode"
            else self.arrival_rate(now)
        )
        demand = lam * self.request_service_time_s() * self.headroom
        want = max(
            self.min_replicas,
            math.ceil(demand / self._slots_per_replica(role) - 1e-9),
        )
        if self.slo is not None and self._alert_blames(role or "unified"):
            want = max(want, self._active(role) + 1)
        cap = (
            min(self.max_replicas, self._pool_size(role))
            if role is not None and self.disaggregated
            else self.max_replicas
        )
        return min(max(want, self.min_replicas), cap)

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One policy evaluation: damp each pool's instantaneous
        desired count through hysteresis + cooldown, then park or
        un-park at most ONE replica fleet-wide.  Pools are visited in
        fixed order (prefill first) so a tick where both pools want to
        move is deterministic.  Returns the action taken or ``None``."""
        t = self._clock() if now is None else float(now)
        total_desired = 0
        action: Optional[str] = None
        for role in self.role_order:
            pool = None if not self.disaggregated else role
            desired = self.desired_replicas(t, pool)
            active = self._active(pool)
            total_desired += desired
            trend = self._trend[role]
            direction = (desired > active) - (desired < active)
            if direction == 0:
                trend[0] = trend[1] = 0
                continue
            if direction == trend[0]:
                trend[1] += 1
            else:
                trend[0], trend[1] = direction, 1
            if action is not None or trend[1] < self.hold_ticks:
                continue
            if (
                self._last_resize_at is not None
                and t - self._last_resize_at < self.cooldown_s
            ):
                continue
            action = (
                self._scale_up(pool) if direction > 0
                else self._scale_down(pool)
            )
            if action is not None:
                self._last_resize_at = t
                trend[0] = trend[1] = 0
        self._g_desired.set(float(total_desired))
        self._g_active.set(float(self._active()))
        return action

    # ------------------------------------------------------------------ #
    # actuation                                                          #
    # ------------------------------------------------------------------ #

    def _record(self, detail: str) -> None:
        if self.recorder is not None:
            try:
                self.recorder.record("autoscale", detail=detail)
            except Exception:  # noqa: BLE001 - telemetry is best-effort
                pass

    def _scale_down(self, role: Optional[str] = None) -> Optional[str]:
        # The floor is PER POOL: a starved decode pool cannot be robbed
        # to feed prefill, however hard the admission window burns.
        if self._active(role) <= self.min_replicas:
            return None
        # Deterministic victim: the last in-rotation replica by name —
        # scale-up un-parks in the reverse order, so the fleet breathes
        # through the same replicas and their warm compiled programs.
        candidates = sorted(
            name for name, rep in self.router.replicas.items()
            if rep.in_rotation
            and (role is None or self.roles.get(name) == role)
        )
        victim = candidates[-1]
        moved = self.router.drain_replica(victim)
        self.parked.append(victim)
        self._c_resizes.inc(direction="down")
        pool = "" if role is None else f" [{role}]"
        self._record(
            f"down {victim}{pool}: {len(moved)} in-flight moved, "
            f"{self._active()} active"
        )
        return f"down:{victim}"

    def _scale_up(self, role: Optional[str] = None) -> Optional[str]:
        cap = (
            min(self.max_replicas, self._pool_size(role))
            if role is not None else self.max_replicas
        )
        if self._active(role) >= cap:
            return None
        # LIFO within the pool: the most recently parked (warmest)
        # compatible replica returns first.
        name = next(
            (n for n in reversed(self.parked)
             if role is None or self.roles.get(n) == role),
            None,
        )
        if name is None:
            return None
        self.parked.remove(name)
        rep = self.router.replicas[name]
        rep.draining = False
        rep.engine.resume_serving()
        self._c_resizes.inc(direction="up")
        pool = "" if role is None else f" [{role}]"
        self._record(f"up {name}{pool}: {self._active()} active")
        return f"up:{name}"


__all__ = ["Autoscaler"]
