"""Fleet serving: the layer above one engine.

:mod:`torchgpipe_tpu.serving` ends at ONE continuous-batching engine on
one set of params.  This package is the horizontal story on top of it —
the "millions of users" tier (docs/serving.md, fleet section):

* :mod:`~torchgpipe_tpu.fleet.router` — N replicas behind one
  ``submit()``: session affinity, power-of-two-choices balancing on the
  shared :class:`~torchgpipe_tpu.obs.MetricsRegistry` occupancy/TPOT
  series, and drain-aware failover riding the existing
  ``CheckpointManager`` / ``Engine.restore_requests`` path — a replica
  dying mid-generation resumes its in-flight requests on a SURVIVOR,
  greedy outputs bitwise-equal to an undisturbed run.
* :mod:`~torchgpipe_tpu.fleet.prefix_cache` — a radix trie over
  :class:`~torchgpipe_tpu.serving.cache_pool.CachePool`: requests
  sharing a system prompt reuse KV slots through refcounted donor pins
  and one fixed-shape copy program; reuse is bitwise vs cold prefill.
* :mod:`~torchgpipe_tpu.fleet.speculative` — a draft model through the
  same pipelined decode path, target-verified in one chunked
  ``decode_slots`` step that REUSES the engine's ``g > 1`` prefill
  program, so the steady-state program count stays fixed
  (``analysis.serving.certify_speculative``).
* :mod:`~torchgpipe_tpu.fleet.trace` — a deterministic synthetic
  million-request trace generator (ragged, bursty, shared-prefix
  tenants) driving ``bench.py --fleet``, so fleet claims are measured,
  not asserted.
* :mod:`~torchgpipe_tpu.fleet.autoscaler` — :class:`Autoscaler`:
  replica count as a control loop — Little's-law pricing off the
  measured ``CostModel`` + MMPP arrival rates, SLO-burn override,
  hysteresis/cooldown damping; scale-down reuses the router's drain
  path (no in-flight request dropped), scale-up re-opens a parked
  replica's admissions.
* :mod:`~torchgpipe_tpu.fleet.migration` — phase-disaggregated
  serving's handoff: a prefill replica's finished prompt (KV rows +
  first token) ships to a decode replica through one fixed-shape
  ``migrate_ingest`` program; the continued greedy stream is bitwise
  what a unified replica would have produced.  The router drives it
  when its replicas declare ``role="prefill"`` / ``role="decode"``.

    from torchgpipe_tpu import fleet, serving
    shared = obs.MetricsRegistry()
    router = fleet.Router({
        name: serving.Engine(cfg, flat, num_slots=4, max_len=64,
                             registry=shared.labeled(replica=name))
        for name in ("r0", "r1")
    }, registry=shared)
    rid = router.submit(prompt, 32, session="user-1")
    router.run()
    tokens = router.result(rid)
"""

from __future__ import annotations

from torchgpipe_tpu.fleet.autoscaler import Autoscaler
from torchgpipe_tpu.fleet.migration import (
    MigrationError,
    migrate,
    stage_rows,
    validate_pools,
)
from torchgpipe_tpu.fleet.prefix_cache import RadixPrefixCache
from torchgpipe_tpu.fleet.rollout import RolloutController
from torchgpipe_tpu.fleet.router import (
    Replica,
    ReplicaDied,
    Router,
    RouterRecord,
)
from torchgpipe_tpu.fleet.speculative import SpeculativeEngine
from torchgpipe_tpu.fleet.trace import (
    TraceConfig,
    TraceRequest,
    TraceStats,
    prefill_heavy_config,
    synthetic_trace,
    tenant_prefixes,
    trace_summary,
)

__all__ = [
    "Autoscaler",
    "MigrationError",
    "RadixPrefixCache",
    "Replica",
    "ReplicaDied",
    "RolloutController",
    "Router",
    "RouterRecord",
    "SpeculativeEngine",
    "TraceConfig",
    "TraceRequest",
    "TraceStats",
    "migrate",
    "prefill_heavy_config",
    "stage_rows",
    "synthetic_trace",
    "tenant_prefixes",
    "trace_summary",
    "validate_pools",
]
