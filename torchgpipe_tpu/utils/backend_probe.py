"""Dead-accelerator-tunnel defense shared by the driver entry points.

On remote-attached TPUs a dead tunnel makes backend init either hang
forever inside the plugin (no in-process watchdog can interrupt it) or
raise UNAVAILABLE — both observed.  Probe init in a SUBPROCESS with a
timeout; callers fall back to the host CPU platform when unreachable
(bench.py labels its metric, __graft_entry__ prints a warning).
"""

from __future__ import annotations

import os
import subprocess
import sys


def backend_reachable(timeout: float = 300.0) -> bool:
    """True if ``jax.devices()`` completes in a fresh interpreter.

    The probe costs one duplicate backend init on healthy runs (remote
    tunnels take a while); set ``TGPU_SKIP_BACKEND_PROBE=1`` to skip it
    when the environment is known-good.
    """
    if os.environ.get("TGPU_SKIP_BACKEND_PROBE"):
        return True
    try:
        # DEVNULL, not pipes: plugin helper processes inheriting a pipe fd
        # would keep communicate() from ever seeing EOF after the kill —
        # re-introducing the very hang this probe exists to prevent.
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False
