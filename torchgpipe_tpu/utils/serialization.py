"""Model persistence: named flat state dicts + file save/load.

Counterpart of the reference's persistence story (SURVEY.md §5
"checkpoint/resume"): the reference relies on ``nn.Module.state_dict`` with
keys ``partitions.<j>.<name>...`` (tested at reference
tests/test_gpipe.py:434, 488-497).  Here params/state are explicit pytrees,
so persistence is a pure naming transform: flatten per-stage pytrees into a
``{key: ndarray}`` dict with the same ``partitions.<stage>.<layer>...`` key
shape, and load into an initialized template by exact key/shape match
(construct → ``init`` → ``load_state_dict``, the torch flow).

File format is ``.npz`` via :func:`save` / :func:`load` — host-portable,
no framework pickle.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Sequence, Tuple

import jax
import numpy as np

Pytree = Any


def _leaf_paths(tree: Pytree) -> List[Tuple[str, Any]]:
    return [
        (jax.tree_util.keystr(path), leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
    ]


def state_dict(
    model: Any,
    params: Sequence[Sequence[Pytree]],
    state: Sequence[Sequence[Pytree]],
) -> Dict[str, np.ndarray]:
    """Flat named mapping for a :class:`~torchgpipe_tpu.gpipe.GPipe` model.

    Keys: ``partitions.<stage>.<layer_name>.params<path>`` and
    ``...state<path>`` — stage and layer identity preserved, like the
    reference's ``partitions.<j>.<name>`` keys
    (reference: torchgpipe/gpipe.py:257-285 container protocol +
    tests/test_gpipe.py:434).
    """
    out: Dict[str, np.ndarray] = {}

    def put(key: str, leaf) -> None:
        if key in out:
            raise ValueError(
                f"duplicate state-dict key {key!r}: layer names must be "
                "unique within a stage (see layers.named) or the checkpoint "
                "would silently drop parameters"
            )
        out[key] = np.asarray(leaf)

    for j, part in enumerate(model.partitions):
        for li, layer in enumerate(part):
            base = f"partitions.{j}.{layer.name}"
            for path, leaf in _leaf_paths(params[j][li]):
                put(f"{base}.params{path}", leaf)
            for path, leaf in _leaf_paths(state[j][li]):
                put(f"{base}.state{path}", leaf)
    return out


def load_state_dict(
    model: Any,
    params: Sequence[Sequence[Pytree]],
    state: Sequence[Sequence[Pytree]],
    d: Dict[str, np.ndarray],
) -> Tuple[List[List[Pytree]], List[List[Pytree]]]:
    """Replace every leaf of an initialized ``(params, state)`` template with
    the identically-keyed array from ``d``.

    Strict: missing keys, unexpected keys, and shape mismatches all raise
    (the ``load_state_dict(strict=True)`` contract).  Returns new
    ``(params, state)`` placed on the model's stage devices.
    """
    remaining = dict(d)

    def rebuild(kind: str, template):
        rebuilt = []
        for j, part in enumerate(model.partitions):
            stage_items = []
            for li, layer in enumerate(part):
                base = f"partitions.{j}.{layer.name}.{kind}"
                leaves, treedef = jax.tree_util.tree_flatten_with_path(
                    template[j][li]
                )
                new_leaves = []
                for path, leaf in leaves:
                    key = f"{base}{jax.tree_util.keystr(path)}"
                    if key not in remaining:
                        raise KeyError(f"state dict is missing {key!r}")
                    arr = remaining.pop(key)
                    if tuple(arr.shape) != tuple(leaf.shape):
                        raise ValueError(
                            f"shape mismatch for {key!r}: saved {arr.shape}, "
                            f"model expects {leaf.shape}"
                        )
                    new_leaves.append(np.asarray(arr).astype(leaf.dtype))
                stage_items.append(
                    jax.tree_util.tree_unflatten(
                        jax.tree_util.tree_structure(template[j][li]),
                        new_leaves,
                    )
                )
            rebuilt.append(stage_items)
        return tuple(rebuilt)

    new_params = rebuild("params", params)
    new_state = rebuild("state", state)
    if remaining:
        raise KeyError(
            f"unexpected keys in state dict: {sorted(remaining)[:5]}"
            + ("..." if len(remaining) > 5 else "")
        )
    return model.place(new_params), model.place(new_state)


def save(path: str, d: Dict[str, np.ndarray]) -> None:
    """Write a flat state dict to ``path`` (.npz) — atomically.

    The bytes are staged in a temp file in the SAME directory, flushed and
    fsync'd, then renamed over ``path``: a crash (or preemption) mid-save
    can never truncate a previously-good checkpoint — the reader sees
    either the old complete file or the new complete file.  Matches
    ``np.savez``'s naming: ``.npz`` is appended when missing.
    """
    final = _abs(path)
    if not final.endswith(".npz"):
        final += ".npz"
    tmp = f"{final}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **d)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load(path: str) -> Dict[str, np.ndarray]:
    """Read a flat state dict written by :func:`save`."""
    with np.load(path) as f:
        return {k: f[k] for k in f.files}


# --------------------------------------------------------------------- #
# Sharded training-state checkpoints (SPMD engine / multi-host)         #
# --------------------------------------------------------------------- #


def save_sharded(path: str, tree: Pytree, *, overwrite: bool = True) -> None:
    """Persist an arbitrary pytree of (possibly sharded) jax arrays with
    orbax — params, optimizer state, step counters, all in one tree.

    This is the checkpoint/resume story for the SPMD engine: stacked block
    params sharded over pp (and tp/ep weight shards) are written from their
    device shards; on multi-host deployments each host writes only the
    shards it owns.  The MPMD :func:`state_dict`/:func:`save` path remains
    for reference-style flat ``.npz`` persistence.

    ``overwrite=True`` (default, matching :func:`save`'s npz semantics)
    replaces an existing checkpoint at ``path`` — the periodic
    save-to-fixed-path loop — by writing the new checkpoint to a sibling
    temp directory FIRST and swapping afterwards, so a crash mid-save never
    destroys the previous copy (at worst it leaves it under
    ``<path>.old``).  Pass ``False`` to refuse clobbering.

    Multi-host: every process calls this (orbax writes each host's shards),
    but the directory swap is filesystem surgery on shared storage, so only
    process 0 performs it, fenced by global barriers — before the save (so
    no host writes into a half-deleted temp dir) and around the swap (so no
    host proceeds, e.g. into a restore, while the rename is in flight).
    """
    import shutil

    import orbax.checkpoint as ocp

    final = _abs(path)

    def _barrier(tag: str) -> None:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(f"save_sharded:{tag}")

    with ocp.StandardCheckpointer() as ckptr:
        # The branch depends ONLY on the (host-consistent) ``overwrite``
        # argument — never on a per-host filesystem probe, which can
        # disagree across hosts (stale NFS attribute caches) and would
        # strand some processes at a collective barrier the others never
        # reach.
        if overwrite:
            tmp, old = final + ".tmp", final + ".old"
            if jax.process_index() == 0:
                shutil.rmtree(tmp, ignore_errors=True)
            _barrier("pre-save")
            ckptr.save(tmp, tree)
            ckptr.wait_until_finished()
            _barrier("post-save")
            if jax.process_index() == 0:
                shutil.rmtree(old, ignore_errors=True)
                if os.path.exists(final):
                    os.rename(final, old)
                os.rename(tmp, final)
                shutil.rmtree(old, ignore_errors=True)
            _barrier("post-swap")
        else:
            ckptr.save(final, tree)


def restore_sharded(path: str, template: Pytree) -> Pytree:
    """Restore a tree written by :func:`save_sharded`.

    ``template`` supplies structure, dtypes and — crucially — shardings:
    pass the live initialized tree (e.g. from ``SpmdGPipe.init``, with
    optimizer state run through ``SpmdGPipe.place_tree`` so scalar counters
    are mesh-committed too) or a matching tree of ``jax.ShapeDtypeStruct``s
    with ``sharding`` set; the restored arrays come back on the same mesh
    layout, so training resumes without a re-place.
    """
    import orbax.checkpoint as ocp

    abstract = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(
            a.shape, a.dtype, sharding=getattr(a, "sharding", None)
        ),
        template,
    )
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(_abs(path), abstract)


def _abs(path: str) -> str:
    return os.path.abspath(os.fspath(path))
