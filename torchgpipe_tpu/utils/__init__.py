"""Shared small utilities."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Pytree = Any


def tree_allclose(a: Pytree, b: Pytree, *, rtol: float = 1e-5, atol: float = 1e-6) -> bool:
    """Structural + numerical equality of two pytrees (test helper)."""
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    if ta != tb:
        return False
    return all(
        x.shape == y.shape and jnp.allclose(x, y, rtol=rtol, atol=atol)
        for x, y in zip(la, lb)
    )


def param_count(tree: Pytree) -> int:
    """Total number of scalar parameters in a pytree."""
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(tree))


def tree_finite(tree: Pytree) -> jax.Array:
    """TRACEABLE all-finite reduction over every inexact leaf — the
    in-program twin of :func:`torchgpipe_tpu.resilience.guard._all_finite`
    (which host-syncs).  The megastep scan threads this through its carry
    so NaN skip-step semantics survive inside one compiled program: it
    must cover exactly what the StepGuard's host-side check covers (the
    whole step output) for megastep(K) to bitwise-match K guarded steps.
    """
    ok = jnp.bool_(True)
    for leaf in jax.tree_util.tree_leaves(tree):
        if jnp.issubdtype(jnp.result_type(leaf), jnp.inexact):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    return ok


def host_device() -> Any:
    """Context placing computation on the host CPU backend (no-op fallback
    when unavailable).

    Used by the engines' ``init``: initialization is hundreds of tiny ops
    (one per weight); dispatching each through an accelerator round-trip
    dominates start-up on remote-attached TPUs, so init on host, then
    transfer placed pytrees once.
    """
    import contextlib

    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except Exception:
        return contextlib.nullcontext()
    return jax.default_device(cpu)
