"""Input-pipeline utilities: device prefetching.

The reference's data story is the rank-aware
``DistributedGPipeDataLoader`` (reference: torchgpipe/distributed/
gpipe.py:197-275, mirrored in :mod:`torchgpipe_tpu.distributed`); on TPU
the other half of the story is keeping the host→device copy off the
critical path.  ``jax.device_put`` is asynchronous, so holding a small
queue of already-transferred batches overlaps the next batch's transfer
(and any host-side preprocessing in the iterator) with the current step's
compute — the standard double-buffering recipe.
"""

from __future__ import annotations

import collections
from typing import Any, Iterable, Iterator, Optional

import jax

Pytree = Any


def prefetch_to_device(
    iterable: Iterable[Pytree],
    size: int = 2,
    device: Optional[Any] = None,
) -> Iterator[Pytree]:
    """Yield batches from ``iterable`` with ``size`` transfers in flight.

    Each batch (any pytree of arrays) is committed to ``device`` (or a
    ``NamedSharding`` — pass the sharding object itself) before the
    consumer needs it.  ``size=2`` double-buffers: while the training step
    runs on batch k, batch k+1's host→device copy is already underway.

    The iterator is advanced at most ``size`` items ahead, so host-side
    memory is bounded and generator-backed loaders see backpressure.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    it = iter(iterable)
    queue: collections.deque = collections.deque()

    def enqueue(n: int) -> None:
        for _ in range(n):
            try:
                item = next(it)
            except StopIteration:
                return
            queue.append(jax.device_put(item, device))

    enqueue(size)
    while queue:
        yield queue.popleft()
        enqueue(1)


def pipe_data_sharding(pipe: Any, *, stacked: bool = False) -> Any:
    """The right host→device placement for FULL training batches of
    ``pipe`` — what :func:`prefetch_to_device`'s ``device`` should be.

    * :class:`~torchgpipe_tpu.spmd.SpmdGPipe`: a ``NamedSharding`` over
      the pipe's mesh with the batch dimension split across the data
      axes (dp, ep) — the engine's own data convention, so the compiled
      step consumes the prefetched array without a resharding copy.
      ``stacked=True`` shifts the spec right by one for megastep's
      ``[K, ...]``-stacked batches (the K axis stays unsharded).
    * :class:`~torchgpipe_tpu.gpipe.GPipe`: stage 0's device (micro-
      batches enter the pipeline there); remaining dims ride along.

    Placement is a PERFORMANCE property, not a correctness one — the
    engines' ``jit``/``shard_map`` in-specs reshard mismatched inputs —
    so this helper only has to be good, never exact.
    """
    from torchgpipe_tpu.gpipe import GPipe

    if isinstance(pipe, GPipe):
        return pipe.devices[0]
    from jax.sharding import NamedSharding, PartitionSpec

    batch_axes = tuple(
        a for a in (pipe.dp_axis, pipe.ep_axis) if a is not None
    )
    batch = batch_axes if batch_axes else None
    spec = (
        PartitionSpec(None, batch) if stacked else PartitionSpec(batch)
    )
    return NamedSharding(pipe.mesh, spec)


def prefetch_to_pipe(
    iterable: Iterable[Pytree],
    pipe: Any,
    size: int = 2,
    *,
    stacked: bool = False,
) -> Iterator[Pytree]:
    """:func:`prefetch_to_device` with the placement resolved from the
    pipe (:func:`pipe_data_sharding`) — the one-liner the training-loop
    call sites use::

        for x, y in prefetch_to_pipe(loader, pipe):
            loss, params, opt_state = guard(params, opt_state, x, y)

    Each yielded batch (any pytree — ``(x, y)`` tuples included) is
    already committed to the engine's devices while the PREVIOUS step
    computes, so the step dispatch never waits on a host→device copy
    and the iterator's host-side work (tokenization, augmentation)
    overlaps device compute.  ``stacked=True`` places megastep's
    ``[K, ...]``-stacked batches (leading K axis unsharded).
    """
    return prefetch_to_device(
        iterable, size, device=pipe_data_sharding(pipe, stacked=stacked)
    )


def global_batch_from_local(
    mesh: Any,
    spec: Any,
    local_batch: Pytree,
) -> Pytree:
    """Assemble a GLOBAL sharded batch from each process's LOCAL shard.

    The multi-host data recipe (docs/multihost.md): every process loads
    only its own slice of the global batch (e.g. its dp lanes' examples)
    and this stitches them into one global ``jax.Array`` sharded by
    ``spec`` over ``mesh`` — no host ever holds, or sends, the full batch.
    Wraps ``jax.make_array_from_process_local_data``, which infers the
    global shape from the local one and the sharding's process layout.

    Single-process (all devices addressable) it degrades to a plain
    ``device_put``, so the same input pipeline runs everywhere.

    ``spec`` is a ``PartitionSpec`` applied to every leaf of the batch
    pytree (the engines' data convention: batch dim sharded over the data
    axes, e.g. ``P(("dp", "ep"))``).
    """
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    if sharding.is_fully_addressable:
        return jax.device_put(local_batch, sharding)
    return jax.tree_util.tree_map(
        lambda leaf: jax.make_array_from_process_local_data(sharding, leaf),
        local_batch,
    )
