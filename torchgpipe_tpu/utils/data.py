"""Input-pipeline utilities: device prefetching.

The reference's data story is the rank-aware
``DistributedGPipeDataLoader`` (reference: torchgpipe/distributed/
gpipe.py:197-275, mirrored in :mod:`torchgpipe_tpu.distributed`); on TPU
the other half of the story is keeping the host→device copy off the
critical path.  ``jax.device_put`` is asynchronous, so holding a small
queue of already-transferred batches overlaps the next batch's transfer
(and any host-side preprocessing in the iterator) with the current step's
compute — the standard double-buffering recipe.
"""

from __future__ import annotations

import collections
from typing import Any, Iterable, Iterator, Optional

import jax

Pytree = Any


def prefetch_to_device(
    iterable: Iterable[Pytree],
    size: int = 2,
    device: Optional[Any] = None,
) -> Iterator[Pytree]:
    """Yield batches from ``iterable`` with ``size`` transfers in flight.

    Each batch (any pytree of arrays) is committed to ``device`` (or a
    ``NamedSharding`` — pass the sharding object itself) before the
    consumer needs it.  ``size=2`` double-buffers: while the training step
    runs on batch k, batch k+1's host→device copy is already underway.

    The iterator is advanced at most ``size`` items ahead, so host-side
    memory is bounded and generator-backed loaders see backpressure.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    it = iter(iterable)
    queue: collections.deque = collections.deque()

    def enqueue(n: int) -> None:
        for _ in range(n):
            try:
                item = next(it)
            except StopIteration:
                return
            queue.append(jax.device_put(item, device))

    enqueue(size)
    while queue:
        yield queue.popleft()
        enqueue(1)


def global_batch_from_local(
    mesh: Any,
    spec: Any,
    local_batch: Pytree,
) -> Pytree:
    """Assemble a GLOBAL sharded batch from each process's LOCAL shard.

    The multi-host data recipe (docs/multihost.md): every process loads
    only its own slice of the global batch (e.g. its dp lanes' examples)
    and this stitches them into one global ``jax.Array`` sharded by
    ``spec`` over ``mesh`` — no host ever holds, or sends, the full batch.
    Wraps ``jax.make_array_from_process_local_data``, which infers the
    global shape from the local one and the sharding's process layout.

    Single-process (all devices addressable) it degrades to a plain
    ``device_put``, so the same input pipeline runs everywhere.

    ``spec`` is a ``PartitionSpec`` applied to every leaf of the batch
    pytree (the engines' data convention: batch dim sharded over the data
    axes, e.g. ``P(("dp", "ep"))``).
    """
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    if sharding.is_fully_addressable:
        return jax.device_put(local_batch, sharding)
    return jax.tree_util.tree_map(
        lambda leaf: jax.make_array_from_process_local_data(sharding, leaf),
        local_batch,
    )
