"""Input-pipeline utilities: device prefetching and sequence packing.

The reference's data story is the rank-aware
``DistributedGPipeDataLoader`` (reference: torchgpipe/distributed/
gpipe.py:197-275, mirrored in :mod:`torchgpipe_tpu.distributed`); on TPU
the other half of the story is keeping the host→device copy off the
critical path.  ``jax.device_put`` is asynchronous, so holding a small
queue of already-transferred batches overlaps the next batch's transfer
(and any host-side preprocessing in the iterator) with the current step's
compute — the standard double-buffering recipe.

The second half of this module is **sequence packing** for ragged
corpora: GPipe-style pipelining needs fixed micro-batch shapes, so
variable-length documents are PACKED into the fixed ``[B, S]`` blocks
the engines already certify instead of padded to them.  The packer
(:func:`pack_documents`) is a deterministic greedy first-fit over
documents — no document is ever split across blocks, packing is a pure
function of the document list (resume replays it bit-for-bit) — and
each block carries ``segment_ids`` (0 = pad, 1.. per document) plus
per-token ``positions`` that reset at document boundaries, which is
what the segment-aware attention mask and packed rotary embeddings in
:mod:`torchgpipe_tpu.models.transformer` consume.  ``labels`` are the
within-document next tokens and ``weights`` mark the REAL supervised
positions, so the cross-entropy reduction weights by real tokens, not
block size (:func:`torchgpipe_tpu.models.transformer.
packed_cross_entropy`).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Iterable, Iterator, List, Optional, Sequence, Tuple

import jax
import numpy as np

Pytree = Any


def prefetch_to_device(
    iterable: Iterable[Pytree],
    size: int = 2,
    device: Optional[Any] = None,
) -> Iterator[Pytree]:
    """Yield batches from ``iterable`` with ``size`` transfers in flight.

    Each batch (any pytree of arrays) is committed to ``device`` (or a
    ``NamedSharding`` — pass the sharding object itself) before the
    consumer needs it.  ``size=2`` double-buffers: while the training step
    runs on batch k, batch k+1's host→device copy is already underway.

    The iterator is advanced at most ``size`` items ahead, so host-side
    memory is bounded and generator-backed loaders see backpressure.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    it = iter(iterable)
    queue: collections.deque = collections.deque()

    def enqueue(n: int) -> None:
        for _ in range(n):
            try:
                item = next(it)
            except StopIteration:
                return
            queue.append(jax.device_put(item, device))

    enqueue(size)
    while queue:
        yield queue.popleft()
        enqueue(1)


def pipe_data_sharding(pipe: Any, *, stacked: bool = False) -> Any:
    """The right host→device placement for FULL training batches of
    ``pipe`` — what :func:`prefetch_to_device`'s ``device`` should be.

    * :class:`~torchgpipe_tpu.spmd.SpmdGPipe`: a ``NamedSharding`` over
      the pipe's mesh with the batch dimension split across the data
      axes (dp, ep) — the engine's own data convention, so the compiled
      step consumes the prefetched array without a resharding copy.
      ``stacked=True`` shifts the spec right by one for megastep's
      ``[K, ...]``-stacked batches (the K axis stays unsharded).
    * :class:`~torchgpipe_tpu.gpipe.GPipe`: stage 0's device (micro-
      batches enter the pipeline there); remaining dims ride along.

    Placement is a PERFORMANCE property, not a correctness one — the
    engines' ``jit``/``shard_map`` in-specs reshard mismatched inputs —
    so this helper only has to be good, never exact.
    """
    from torchgpipe_tpu.gpipe import GPipe

    if isinstance(pipe, GPipe):
        return pipe.devices[0]
    from jax.sharding import NamedSharding, PartitionSpec

    batch_axes = tuple(
        a for a in (pipe.dp_axis, pipe.ep_axis) if a is not None
    )
    batch = batch_axes if batch_axes else None
    spec = (
        PartitionSpec(None, batch) if stacked else PartitionSpec(batch)
    )
    return NamedSharding(pipe.mesh, spec)


def prefetch_to_pipe(
    iterable: Iterable[Pytree],
    pipe: Any,
    size: int = 2,
    *,
    stacked: bool = False,
) -> Iterator[Pytree]:
    """:func:`prefetch_to_device` with the placement resolved from the
    pipe (:func:`pipe_data_sharding`) — the one-liner the training-loop
    call sites use::

        for x, y in prefetch_to_pipe(loader, pipe):
            loss, params, opt_state = guard(params, opt_state, x, y)

    Each yielded batch (any pytree — ``(x, y)`` tuples included) is
    already committed to the engine's devices while the PREVIOUS step
    computes, so the step dispatch never waits on a host→device copy
    and the iterator's host-side work (tokenization, augmentation)
    overlaps device compute.  ``stacked=True`` places megastep's
    ``[K, ...]``-stacked batches (leading K axis unsharded).
    """
    return prefetch_to_device(
        iterable, size, device=pipe_data_sharding(pipe, stacked=stacked)
    )


# --------------------------------------------------------------------- #
# sequence packing (ragged corpora into fixed [B, S] blocks)            #
# --------------------------------------------------------------------- #


@dataclasses.dataclass(frozen=True)
class Packing:
    """The result of :func:`pack_documents`: every document placed into
    fixed-length blocks, ready to slice into fixed ``[B, S]`` batches.

    Arrays are host-side ``np.ndarray`` (the input pipeline's domain);
    ``[R, S]`` with ``R`` the number of packed blocks:

    * ``tokens`` — the documents' tokens, back to back; ``pad_id`` fills
      each block's tail.
    * ``segment_ids`` — ``0`` on pad, ``1..k`` numbering the documents
      WITHIN each block (the block-diagonal attention-mask term).
    * ``positions`` — 0-based position of each token within ITS document
      (the packed rotary/learned-position index; resets per document).
    * ``labels`` / ``weights`` — within-document next token (causal-LM
      objective) and a ``1.0`` weight at every REAL supervised position;
      the last token of each document and all pad carry weight ``0.0``.
    * ``doc_locs`` — per input document ``(row, offset, length)``: where
      it landed.  The order is the input order; no document is split.
    """

    tokens: np.ndarray        # [R, S] int32
    segment_ids: np.ndarray   # [R, S] int32
    positions: np.ndarray     # [R, S] int32
    labels: np.ndarray        # [R, S] int32
    weights: np.ndarray       # [R, S] float32
    doc_locs: Tuple[Tuple[int, int, int], ...]
    block_len: int
    pad_id: int

    @property
    def n_blocks(self) -> int:
        return int(self.tokens.shape[0])

    @property
    def n_real_tokens(self) -> int:
        return int(np.sum(self.segment_ids != 0))

    @property
    def pad_fraction(self) -> float:
        """Fraction of block positions that hold pad, not document."""
        total = self.tokens.size
        return 1.0 - (self.n_real_tokens / total) if total else 0.0


def pack_documents(
    docs: Sequence[Any], block_len: int, *, pad_id: int = 0
) -> Packing:
    """Deterministic greedy FIRST-FIT packing of ``docs`` into
    ``block_len``-token blocks.

    Each document (a 1-D int token array) is placed whole into the first
    open block with room, else a new block opens — a pure function of
    the document list, so re-packing the same corpus (e.g. on resume)
    replays the identical layout.  A document longer than ``block_len``
    is a :class:`ValueError`: packing never splits documents (a split
    document's second half would attend nothing — train on shorter
    documents or raise ``block_len``).
    """
    if block_len < 2:
        raise ValueError(f"block_len must be >= 2, got {block_len}")
    arrs = [np.asarray(d, np.int32).reshape(-1) for d in docs]
    for i, a in enumerate(arrs):
        if a.size < 1:
            raise ValueError(f"document {i} is empty")
        if a.size > block_len:
            raise ValueError(
                f"document {i} has {a.size} tokens > block_len="
                f"{block_len}; packing never splits a document across "
                "blocks — raise block_len or pre-chunk the corpus"
            )
    free: List[int] = []           # free tokens per open block
    rows: List[List[np.ndarray]] = []
    locs: List[Tuple[int, int, int]] = []
    for a in arrs:
        for r, f in enumerate(free):
            if a.size <= f:
                row = r
                break
        else:
            row = len(free)
            free.append(block_len)
            rows.append([])
        locs.append((row, block_len - free[row], a.size))
        rows[row].append(a)
        free[row] -= a.size
    R = len(rows)
    tokens = np.full((R, block_len), pad_id, np.int32)
    seg = np.zeros((R, block_len), np.int32)
    pos = np.zeros((R, block_len), np.int32)
    labels = np.full((R, block_len), pad_id, np.int32)
    weights = np.zeros((R, block_len), np.float32)
    per_row_seg = [0] * R
    for a, (r, off, n) in zip(arrs, locs):
        per_row_seg[r] += 1
        tokens[r, off:off + n] = a
        seg[r, off:off + n] = per_row_seg[r]
        pos[r, off:off + n] = np.arange(n)
        # Within-document shift: position i predicts token i+1 of the
        # SAME document; the document's last token supervises nothing.
        labels[r, off:off + n - 1] = a[1:]
        weights[r, off:off + n - 1] = 1.0
    return Packing(
        tokens=tokens, segment_ids=seg, positions=pos,
        labels=labels, weights=weights, doc_locs=tuple(locs),
        block_len=block_len, pad_id=pad_id,
    )


def _batch_of(packing: Packing, rows: np.ndarray) -> Tuple[Pytree, Pytree]:
    """(x, y) for a row-index slice: the engines' packed batch contract
    — ``x`` a dict the packed-aware embedding unpacks, ``y`` the
    labels/weights dict :func:`~torchgpipe_tpu.models.transformer.
    packed_cross_entropy` consumes."""
    x = {
        "tokens": packing.tokens[rows],
        "segment_ids": packing.segment_ids[rows],
        "positions": packing.positions[rows],
    }
    y = {
        "labels": packing.labels[rows],
        "weights": packing.weights[rows],
    }
    return x, y


def packed_batches(
    packing: Packing,
    batch_rows: int,
    *,
    start: int = 0,
) -> Iterator[Tuple[Pytree, Pytree]]:
    """Slice a :class:`Packing` into fixed ``[batch_rows, block_len]``
    batches — every batch the SAME shape (a short final batch is topped
    up with all-pad rows: ``segment_ids == 0`` everywhere, zero loss
    weight — one compiled program serves the whole corpus).

    ``start=k`` resumes at batch ``k``: packing being deterministic, the
    resumed stream is bit-identical to the tail of the original one
    (tested).  Compose with :func:`prefetch_to_pipe` as usual; for the
    megastep path stack K consecutive batches along a leading axis
    (``stacked=True`` placement).
    """
    if batch_rows < 1:
        raise ValueError(f"batch_rows must be >= 1, got {batch_rows}")
    R = packing.n_blocks
    n_batches = -(-R // batch_rows)
    for b in range(start, n_batches):
        idx = np.arange(b * batch_rows, (b + 1) * batch_rows)
        idx = np.minimum(idx, R - 1)
        x, y = _batch_of(packing, idx)
        # Rows past the corpus end become all-pad no-ops rather than
        # repeats of the last block.
        tail = np.arange(batch_rows) + b * batch_rows >= R
        if tail.any():
            for k in ("tokens", "labels"):
                d = x if k in x else y
                d[k] = np.where(tail[:, None], packing.pad_id, d[k])
            x["segment_ids"] = np.where(tail[:, None], 0, x["segment_ids"])
            x["positions"] = np.where(tail[:, None], 0, x["positions"])
            y["weights"] = np.where(tail[:, None], 0.0, y["weights"])
        yield x, y


def padded_batches(
    docs: Sequence[Any],
    block_len: int,
    batch_rows: int,
    *,
    pad_id: int = 0,
    start: int = 0,
) -> Iterator[Tuple[Pytree, Pytree]]:
    """The PADDED baseline over the same documents: one document per
    ``[block_len]`` row, tail padded — the layout whose pad FLOPs
    :func:`pack_documents` exists to reclaim (the ``bench.py --packing``
    rung runs both over one corpus).  ``x`` is a plain ``[B, S]`` token
    array (no segment ids — the un-packed contract); ``y`` carries the
    same labels/weights schema, so ONE loss function serves both paths.
    """
    arrs = [np.asarray(d, np.int32).reshape(-1) for d in docs]
    n_batches = -(-len(arrs) // batch_rows)
    for b in range(start, n_batches):
        chunk = arrs[b * batch_rows:(b + 1) * batch_rows]
        tokens = np.full((batch_rows, block_len), pad_id, np.int32)
        labels = np.full((batch_rows, block_len), pad_id, np.int32)
        weights = np.zeros((batch_rows, block_len), np.float32)
        for r, a in enumerate(chunk):
            if a.size > block_len:
                raise ValueError(
                    f"document has {a.size} tokens > block_len={block_len}"
                )
            tokens[r, :a.size] = a
            labels[r, :a.size - 1] = a[1:]
            weights[r, :a.size - 1] = 1.0
        yield tokens, {"labels": labels, "weights": weights}


def real_token_fraction(x: Pytree, *, pad_id: int = 0) -> float:
    """Fraction of batch positions holding REAL tokens — the honest-MFU
    scale (:class:`torchgpipe_tpu.obs.StepReporter`'s
    ``real_token_fraction``): a packed batch (dict with
    ``segment_ids``) counts non-zero segments; a plain token array
    counts everything outside each row's TRAILING run of ``pad_id``
    (leading/interior ``pad_id`` tokens may be real vocabulary)."""
    if isinstance(x, dict) and "segment_ids" in x:
        seg = np.asarray(x["segment_ids"])
        return float(np.mean(seg != 0)) if seg.size else 0.0
    a = np.asarray(x)
    if a.ndim != 2 or a.size == 0:
        return 1.0
    rev = a[:, ::-1] != pad_id
    # Trailing pad run per row = leading run of pad_id in the reversal.
    trailing = np.where(
        rev.any(axis=1), np.argmax(rev, axis=1), a.shape[1]
    )
    return 1.0 - float(np.sum(trailing)) / a.size


def global_batch_from_local(
    mesh: Any,
    spec: Any,
    local_batch: Pytree,
) -> Pytree:
    """Assemble a GLOBAL sharded batch from each process's LOCAL shard.

    The multi-host data recipe (docs/multihost.md): every process loads
    only its own slice of the global batch (e.g. its dp lanes' examples)
    and this stitches them into one global ``jax.Array`` sharded by
    ``spec`` over ``mesh`` — no host ever holds, or sends, the full batch.
    Wraps ``jax.make_array_from_process_local_data``, which infers the
    global shape from the local one and the sharding's process layout.

    Single-process (all devices addressable) it degrades to a plain
    ``device_put``, so the same input pipeline runs everywhere.

    ``spec`` is a ``PartitionSpec`` applied to every leaf of the batch
    pytree (the engines' data convention: batch dim sharded over the data
    axes, e.g. ``P(("dp", "ep"))``).
    """
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    if sharding.is_fully_addressable:
        return jax.device_put(local_batch, sharding)
    return jax.tree_util.tree_map(
        lambda leaf: jax.make_array_from_process_local_data(sharding, leaf),
        local_batch,
    )
