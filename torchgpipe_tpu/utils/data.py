"""Input-pipeline utilities: device prefetching.

The reference's data story is the rank-aware
``DistributedGPipeDataLoader`` (reference: torchgpipe/distributed/
gpipe.py:197-275, mirrored in :mod:`torchgpipe_tpu.distributed`); on TPU
the other half of the story is keeping the host→device copy off the
critical path.  ``jax.device_put`` is asynchronous, so holding a small
queue of already-transferred batches overlaps the next batch's transfer
(and any host-side preprocessing in the iterator) with the current step's
compute — the standard double-buffering recipe.
"""

from __future__ import annotations

import collections
from typing import Any, Iterable, Iterator, Optional

import jax

Pytree = Any


def prefetch_to_device(
    iterable: Iterable[Pytree],
    size: int = 2,
    device: Optional[Any] = None,
) -> Iterator[Pytree]:
    """Yield batches from ``iterable`` with ``size`` transfers in flight.

    Each batch (any pytree of arrays) is committed to ``device`` (or a
    ``NamedSharding`` — pass the sharding object itself) before the
    consumer needs it.  ``size=2`` double-buffers: while the training step
    runs on batch k, batch k+1's host→device copy is already underway.

    The iterator is advanced at most ``size`` items ahead, so host-side
    memory is bounded and generator-backed loaders see backpressure.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    it = iter(iterable)
    queue: collections.deque = collections.deque()

    def enqueue(n: int) -> None:
        for _ in range(n):
            try:
                item = next(it)
            except StopIteration:
                return
            queue.append(jax.device_put(item, device))

    enqueue(size)
    while queue:
        yield queue.popleft()
        enqueue(1)
