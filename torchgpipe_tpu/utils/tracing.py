"""Pipeline timeline tracing.

Counterpart of the reference's timeline/ablation tooling (SURVEY.md §5:
benchmarks/unet-timeline samples GPU utilization from a side process;
the balancer has its own profiler).  TPU-native redesign: the engine itself
records per-cell (micro-batch, stage) dispatch/ready intervals — no side
process, no `nvidia-smi` — plus a thin wrapper over the JAX device profiler
for XLA-level traces viewable in TensorBoard/Perfetto.

Usage::

    tracer = Timeline()
    model = GPipe(layers, balance, chunks=8, tracer=tracer)
    model.value_and_grad(...)
    print(tracer.summary())
    tracer.events  # [(name, stage, mbatch, t_start, t_end), ...]

``Timeline.sync=True`` turns the tracer into the *ablation* tool: every cell
is forced to completion before the next is dispatched, serializing the
pipeline — measuring how much of the throughput comes from cross-stage
overlap (the question the reference's unet-timeline experiments answer by
monkey-patching deps/streams, benchmarks/unet-timeline/main.py:22-75).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Any, Callable, Iterator, List, Optional, Tuple

import jax


@dataclasses.dataclass
class TimelineEvent:
    name: str  # "fwd" | "bwd" | "loss" | ...
    stage: int
    mbatch: int
    t_start: float
    t_end: float

    @property
    def duration(self) -> float:
        return self.t_end - self.t_start


class Timeline:
    """Per-cell dispatch recorder for the MPMD engine.

    With ``sync=False`` (default) the recorded interval is the *dispatch*
    cost (JAX is async; device work overlaps).  With ``sync=True`` each cell
    is blocked to completion — true per-cell device time, zero overlap: the
    serialized-pipeline ablation baseline.
    """

    def __init__(self, sync: bool = False) -> None:
        self.sync = sync
        self.events: List[TimelineEvent] = []
        self._t0 = time.perf_counter()

    def reset(self) -> None:
        self.events.clear()
        self._t0 = time.perf_counter()

    def record(
        self,
        name: str,
        stage: int,
        mbatch: int,
        out: Any = None,
        settle: float = 0.0,
    ) -> Any:
        """Record one cell and return ``out`` (so engines can chain
        ``y = tracer.record("fwd", j, i, y)``); blocks on ``out`` when
        ``sync`` is set.  ``settle`` (seconds) sleeps INSIDE the span,
        after the block: the deterministic-straggler slot the MPMD
        schedulers feed from ``resilience.faults.cell_delay_s`` — a
        ``slow_at`` fault plan then both delays the run and shows up in
        the measured per-cell durations the reconciliation reads."""
        t_start = time.perf_counter() - self._t0
        if self.sync and out is not None:
            jax.block_until_ready(out)
        if settle > 0.0:
            time.sleep(settle)
        t_end = time.perf_counter() - self._t0
        self.events.append(TimelineEvent(name, stage, mbatch, t_start, t_end))
        return out

    # ------------------------------------------------------------------ #

    def to_chrome_trace(self, path: str) -> None:
        """Write the recorded cells as a Chrome trace-event JSON.

        Open in ``chrome://tracing`` or https://ui.perfetto.dev: one row
        (tid) per pipeline stage, one slice per (cell, phase) — the visual
        the reference approximates with its nvidia-smi utilization sampler
        (reference: benchmarks/unet-timeline/gpu_utils.py:8-69).  With
        ``sync=True`` slices are true per-cell device durations; without,
        they show the dispatch timeline (overlap visible as stacking).
        """
        import json

        trace = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 0,
                "tid": stage,
                "args": {
                    # stage -1 is the SPMD engines' scan-granularity row
                    # (whole compiled-step spans; the scanned cells are
                    # not host-visible — obs.device_trace shows the
                    # XLA interior).
                    "name": f"stage {stage}" if stage >= 0 else "program",
                },
            }
            for stage in sorted({e.stage for e in self.events})
        ]
        trace += [
            {
                "name": f"{e.name} mb{e.mbatch}",
                "ph": "X",
                "pid": 0,
                "tid": e.stage,
                "ts": e.t_start * 1e6,   # microseconds
                "dur": max(e.duration * 1e6, 0.01),
                "args": {
                    "stage": e.stage,
                    "micro_batch": e.mbatch,
                    "kind": e.name,
                },
            }
            for e in self.events
        ]
        with open(path, "w") as f:
            json.dump({"traceEvents": trace, "displayTimeUnit": "ms"}, f)

    def by_stage(self) -> dict:
        out: dict = {}
        for ev in self.events:
            out.setdefault(ev.stage, []).append(ev)
        return out

    def summary(self) -> str:
        if not self.events:
            return "timeline: no events"
        total = max(ev.t_end for ev in self.events) - min(
            ev.t_start for ev in self.events
        )
        lines = [
            f"timeline: {len(self.events)} cells over {total * 1e3:.1f}ms "
            f"({'sync/serialized' if self.sync else 'async dispatch'})"
        ]
        for stage, evs in sorted(self.by_stage().items()):
            busy = sum(ev.duration for ev in evs)
            lines.append(
                f"  stage {stage}: {len(evs)} cells, "
                f"busy {busy * 1e3:.1f}ms ({100 * busy / total:.0f}%)"
            )
        return "\n".join(lines)


@contextlib.contextmanager
def device_trace(logdir: str) -> Iterator[None]:
    """XLA-level device profile (TensorBoard `logdir`), wrapping
    :func:`jax.profiler.start_trace` — the TPU-native replacement for the
    reference's `nvidia-smi` sampler."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def simulate_pipeline(
    events: List[TimelineEvent],
    n_stages: int,
    schedule: str = "fill_drain",
    virtual_stages: int = 1,
) -> Optional[Tuple[float, float, float]]:
    """Project measured per-cell times onto a pipeline schedule.

    Takes a *sync* timeline (true per-cell device durations) and computes
    the makespan the schedule would achieve with perfect overlap.  For
    ``'fill_drain'``: ``finish(i, j) = max(finish(i-1, j), finish(i, j-1))
    + t(i, j)`` per phase, forward and backward separated by the loss
    barrier.  For ``'1f1b'``: each stage executes its PipeDream-flush op
    order (warm-up ``min(m, n-j)`` forwards, then strict bwd/fwd
    alternation — the same order the MPMD engine dispatches,
    pipeline.py ``run_train_1f1b``) with no global barrier; an op starts
    when its stage is free AND its producer finished (fwd needs the
    upstream fwd; bwd needs the downstream bwd, or the same cell's fwd on
    the last stage).  For ``'interleaved'`` the measured stages are read
    as the ``n_stages`` GLOBAL blocks of a virtual-stage layout: pass
    ``virtual_stages=v`` and the projection lays block ``g`` on device
    ``g % (n_stages//v)`` as chunk ``g // (n_stages//v)`` (the Megatron
    wrap-around), answering "what would this measured run cost
    interleaved on n/v devices?".  For ``'zb'`` the measured fused
    backward is split into equal B/W halves and scheduled by the
    zero-bubble op order — "what would the split backward buy on this
    measured run?" (the 50/50 split is the dense-layer FLOP model; state
    it when quoting).  Returns ``(makespan_seconds,
    busy_fraction, bubble_fraction)``; the bubble can be compared against
    the analytic uniform-cell figure — the gap is stage imbalance.
    """
    if schedule not in ("fill_drain", "1f1b", "interleaved", "zb"):
        raise ValueError(
            "schedule must be 'fill_drain', '1f1b', 'interleaved' or 'zb'"
        )
    if schedule == "interleaved":
        if virtual_stages < 2:
            raise ValueError("interleaved projection needs virtual_stages >= 2")
        if n_stages % virtual_stages != 0:
            raise ValueError(
                f"n_stages ({n_stages}) must divide by virtual_stages "
                f"({virtual_stages}): measured stages become the global "
                "blocks of the virtual layout"
            )
    elif virtual_stages != 1:
        raise ValueError("virtual_stages only applies to 'interleaved'")
    # Aggregate/barrier spans (negative micro-batch or stage: the
    # fill-drain engine's gathered-loss barrier at mb -1, the SPMD
    # engines' whole-program "step" spans at stage -1) are not per-cell
    # observations — the projection is defined over cells only.
    events = [e for e in events if e.mbatch >= 0 and e.stage >= 0]
    if not events:
        return None
    # A timeline spanning several training steps observes each (i, j) cell
    # repeatedly; average the observations into one representative step so
    # makespan and busy time describe the same single step.
    sums: dict = {}
    counts: dict = {}
    for ev in events:
        key = (ev.name, ev.mbatch, ev.stage)
        sums[key] = sums.get(key, 0.0) + ev.duration
        counts[key] = counts.get(key, 0) + 1
    by_phase: dict = {}
    for (name, i, j), total in sums.items():
        by_phase.setdefault(name, {})[(i, j)] = total / counts[(name, i, j)]

    if schedule == "1f1b":
        makespan = _simulate_1f1b(by_phase, n_stages)
    elif schedule == "interleaved":
        makespan = _simulate_interleaved(by_phase, n_stages, virtual_stages)
    elif schedule == "zb":
        makespan = _simulate_zb(by_phase, n_stages)
    elif schedule == "fill_drain":
        makespan = 0.0
        for cells in by_phase.values():
            m = 1 + max(i for i, _ in cells)
            n = 1 + max(j for _, j in cells)
            finish = [[0.0] * n for _ in range(m)]
            for i in range(m):
                for j in range(n):
                    prev = max(
                        finish[i - 1][j] if i else 0.0,
                        finish[i][j - 1] if j else 0.0,
                    )
                    finish[i][j] = prev + cells.get((i, j), 0.0)
            makespan += finish[m - 1][n - 1]
    if makespan is None or makespan <= 0:
        return None
    # busy/bubble are per EXECUTION UNIT: devices (n/v of the measured
    # global blocks) for the interleaved projection, stages otherwise.
    units = (
        n_stages // virtual_stages if schedule == "interleaved" else n_stages
    )
    busy = sum(
        cell for cells in by_phase.values() for cell in cells.values()
    ) / (units * makespan)
    return makespan, busy, 1.0 - busy


@dataclasses.dataclass
class ScheduleProjection:
    """One row of :func:`recommend_schedule`'s ranking."""

    schedule: str  # 'fill_drain' | '1f1b' | 'zb' | 'interleaved'
    devices: int  # device count the projection assumes
    virtual_stages: int  # 1 except for 'interleaved'
    makespan: float
    busy: float
    bubble: float
    note: str  # memory character / projection caveat


def recommend_schedule(
    events: List[TimelineEvent],
    n_stages: int,
    virtual_stages: Tuple[int, ...] = (2,),
) -> List[ScheduleProjection]:
    """Rank the engine's schedules on one measured timeline.

    The reference auto-tunes *balance* from a profile
    (``torchgpipe/balance/__init__.py:38-80``) but offers a
    single schedule; this framework has four, and the right one depends on
    the measured cell times — so the schedule choice gets the same
    profile-then-decide treatment.  Feed the ``sync=True`` timeline of one
    training step (true per-cell device durations) and every applicable
    schedule is projected through :func:`simulate_pipeline`:

    * rows with ``devices == n_stages`` come first, sorted by projected
      makespan — ``rows[0]`` is the recommendation at the measured device
      count;
    * ``'interleaved'`` rows (one per ``v`` in ``virtual_stages`` that
      divides ``n_stages``) follow, also makespan-sorted: they answer
      "what if these measured stages were the global blocks of a
      virtual-stage layout on ``n_stages // v`` devices?" — fewer chips,
      not a same-budget alternative, hence ranked apart;
    * schedules whose projection needs phases the timeline lacks (no
      ``bwd`` events → no 1f1b/zb/interleaved projection: their op
      tables interleave backward cells) and interleaved configs the
      measurement cannot support (micro-batch count not divisible by the
      projected device count) are silently omitted.

    Each row's ``note`` carries the schedule's memory character and any
    projection caveat (zb's 50/50 B/W split model), so the ranking is
    never quoted without its assumptions.

    Only ``fwd``/``bwd`` cells enter the comparison: the 1f1b/zb/
    interleaved op tables schedule exactly those phases, so extra phases
    (e.g. ``loss``) would inflate only fill-drain's makespan — and the
    busy denominators — unevenly.  The rows rank schedule quality on the
    common cell set; quote absolute makespans from
    :func:`simulate_pipeline` if other phases matter.
    """
    events = [ev for ev in events if ev.name in ("fwd", "bwd")]
    rows: List[ScheduleProjection] = []
    same_device = (
        ("fill_drain", "peak in-flight activations grow with chunks m per "
                       "stage; all checkpoint modes"),
        ("1f1b", "peak in-flight <= min(m, n-j) per stage (flat in m); all "
                 "checkpoint modes"),
        ("zb", "split backward fills drain bubbles; projection models B/W "
               "as a 50/50 split of the measured fused backward; engine "
               "modes 'never' (stored residuals) or 'always' "
               "(recompute-in-B)"),
    )
    has_bwd = any(ev.name == "bwd" for ev in events)
    for sched, note in same_device:
        if sched in ("1f1b", "zb") and not has_bwd:
            # Their op orders interleave bwd cells; with no measured bwd
            # the projection would rank a fake (zero-backward) makespan.
            continue
        res = simulate_pipeline(events, n_stages, schedule=sched)
        if res is not None:
            rows.append(
                ScheduleProjection(sched, n_stages, 1, *res, note=note)
            )
    rows.sort(key=lambda r: r.makespan)
    inter: List[ScheduleProjection] = []
    for v in virtual_stages:
        if v < 2 or n_stages % v != 0 or n_stages // v < 2 or not has_bwd:
            continue
        try:
            res = simulate_pipeline(
                events, n_stages, schedule="interleaved", virtual_stages=v
            )
        except ValueError:
            # e.g. the measured micro-batch count not divisible by the
            # projected device count — inapplicable, same as a v that
            # doesn't divide n_stages.
            continue
        if res is not None:
            inter.append(
                ScheduleProjection(
                    "interleaved", n_stages // v, v, *res,
                    note=f"measured stages laid out as {n_stages} global "
                         f"blocks on {n_stages // v} devices (v={v}) — a "
                         "fewer-chips projection, not a same-budget "
                         "alternative",
                )
            )
    inter.sort(key=lambda r: r.makespan)
    return rows + inter


def _list_schedule(
    orders: Any,
    dep_fn: Callable,
    time_fn: Callable,
) -> Optional[float]:
    """Shared dependency-driven list scheduler for the per-schedule
    projections: each unit executes its ``orders`` row in order, an op
    starting when its unit is free AND ``dep_fn(op, j)`` (or None) has
    finished; ``time_fn(op, j)`` prices the op.  Returns the makespan, or
    None on deadlock (cyclic/missing data)."""
    n = len(orders)
    done: dict = {}
    pos = [0] * n
    unit_free = [0.0] * n
    total = sum(len(o) for o in orders)
    scheduled = 0
    while scheduled < total:
        progressed = False
        for j in range(n):
            while pos[j] < len(orders[j]):
                op = orders[j][pos[j]]
                dep = dep_fn(op, j)
                if dep is not None and dep not in done:
                    break
                start = max(
                    unit_free[j], done[dep] if dep is not None else 0.0
                )
                finish = start + time_fn(op, j)
                done[op + (j,)] = finish
                unit_free[j] = finish
                pos[j] += 1
                scheduled += 1
                progressed = True
        if not progressed:
            return None
    return max(unit_free)


def _simulate_interleaved(
    by_phase: dict, n_blocks: int, v: int
) -> Optional[float]:
    """Dependency-driven completion times for the interleaved
    (Megatron virtual pipeline stages) op order.

    Measured cells ``(i, j)`` are read as micro-batch ``i`` on GLOBAL
    block ``j``; the projection places block ``g = c·n + dev`` on device
    ``dev`` as chunk ``c`` (n = n_blocks // v devices) and executes each
    device's table order (:mod:`torchgpipe_tpu.parallel.interleaved`), an
    op starting when its device is free AND its producer finished
    (``_producer``: fwd g needs fwd g-1, bwd g needs bwd g+1, the last
    block's bwd needs its own fwd)."""
    from torchgpipe_tpu.parallel.interleaved import (
        BWD,
        FWD,
        _cell_sequence,
        _producer,
    )

    fwd = by_phase.get("fwd", {})
    bwd = by_phase.get("bwd", {})
    if not fwd:
        return None
    n = n_blocks // v
    m = 1 + max(i for i, _ in fwd)
    if m % n != 0:
        # Same rule the engine enforces (interleaved._check_args /
        # SpmdGPipe validation): Megatron's micro-batch grouping assumes
        # full groups — raise the clear error rather than deadlocking on
        # an inconsistent table into an indistinguishable None.
        raise ValueError(
            f"interleaved projection needs the measured micro-batch count "
            f"({m}) divisible by the device count n_stages//virtual_stages "
            f"({n})"
        )
    orders = [_cell_sequence(n, m, v, j) for j in range(n)]

    def dep_fn(op, j):
        kind, c, i = op
        dep = _producer(n, v, kind, c, i, j)
        if dep is None and kind == BWD:
            # The last global block's backward consumes its own forward
            # (the loss seed).
            return (FWD, c, i, j)
        return dep

    def time_fn(op, j):
        kind, c, i = op
        g = c * n + j  # global block index = the measured stage index
        return (fwd if kind == FWD else bwd).get((i, g), 0.0)

    return _list_schedule(orders, dep_fn, time_fn)


def _simulate_zb(by_phase: dict, n: int) -> Optional[float]:
    """Zero-bubble projection: the measured fused backward splits into a
    B half (activation gradient) and a W half (weight gradient), each
    HALF the measured bwd cell time — the dense-layer FLOP split, and the
    modeling assumption to state when quoting the result.  Op order and
    dependencies come from the zb tables
    (:mod:`torchgpipe_tpu.parallel.zerobubble`)."""
    from torchgpipe_tpu.parallel.zerobubble import (
        B as ZB_B,
        F as ZB_F,
        _dep,
        _zb_sequence,
    )

    fwd = by_phase.get("fwd", {})
    bwd = by_phase.get("bwd", {})
    if not fwd:
        return None
    m = 1 + max(i for i, _ in fwd)
    orders = [_zb_sequence(n, m, j) for j in range(n)]

    def dep_fn(op, j):
        kind, i = op
        dep = _dep(n, kind, i, j)
        if dep is not None:
            return dep  # (kind, i, dev) — already op + (device,) shaped
        if kind == ZB_B and j == n - 1:
            return (ZB_F, i, j)  # loss seed: own forward
        if kind not in (ZB_F, ZB_B):
            return (ZB_B, i, j)  # W after its own B
        return None

    def time_fn(op, j):
        kind, i = op
        if kind == ZB_F:
            return fwd.get((i, j), 0.0)
        return bwd.get((i, j), 0.0) / 2.0  # B and W halves

    return _list_schedule(orders, dep_fn, time_fn)


def _simulate_1f1b(by_phase: dict, n: int) -> Optional[float]:
    """Dependency-driven completion times for the PipeDream-flush order."""
    fwd = by_phase.get("fwd", {})
    bwd = by_phase.get("bwd", {})
    if not fwd:
        return None
    from torchgpipe_tpu.pipeline import one_f1b_orders

    m = 1 + max(i for i, _ in fwd)
    orders = one_f1b_orders(m, n)

    def dep_fn(op, j):
        kind, i = op
        if kind == "fwd":
            return ("fwd", i, j - 1) if j > 0 else None
        return ("bwd", i, j + 1) if j < n - 1 else ("fwd", i, j)

    def time_fn(op, j):
        kind, i = op
        return (fwd if kind == "fwd" else bwd).get((i, j), 0.0)

    return _list_schedule(orders, dep_fn, time_fn)
