"""Chip capability tables for measurement integrity and MFU reporting.

The published bf16 peak matters for two things: computing MFU
(model FLOPs / step time / peak) and *refusing to publish impossible
numbers* — a throughput that implies more than the chip's peak FLOP/s
can only come from a backend that did not actually execute the timed
programs (observed on the remote-tunnel backend: an async dispatch loop
"measured" 613% of peak, and a repeat-execution cache returned
block_until_ready instantly for identical re-dispatched inputs).

No reference counterpart (the reference publishes wall-clock numbers
only, reference: docs/benchmarks.rst); this is the honesty layer the
remote-TPU measurement environment forced.
"""

from __future__ import annotations

from typing import Any, Optional

# Published bf16 peak FLOP/s per chip, keyed by device_kind substring
# (checked in order, so the more specific names come first — e.g. 'v4 lite'
# must hit the v4i row before the plain 'v4' row halves-understates it).
PEAK_BF16_FLOPS = (
    ("v6 lite", 918e12),  # Trillium device_kind is 'TPU v6 lite'
    ("v6e", 918e12),
    ("v5 lite", 197e12),  # v5e device_kind is 'TPU v5 lite'
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4 lite", 138e12),  # v4i
    ("v4i", 138e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def chip_peak_bf16_flops(device: Any) -> Optional[float]:
    """Published bf16 peak FLOP/s for ``device``, or None if unknown."""
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in PEAK_BF16_FLOPS:
        if key in kind:
            return peak
    return None
